"""Fleet-level KV-page live migration.

Coverage:

- pool ``token_rows`` (the gather/scatter index primitive);
- in-process source->dest scheduler roundtrips: cold-dest transfer,
  warm-dest suffix-only transfer (radix prefix reuse), abort paths
  (source stays authoritative), corrupt-payload rejection — all
  asserting TOKEN-EXACT post-migration decode vs. the unmigrated
  sequential-GPTGenerator oracle and zero leaked pool pages;
- the hardened control-plane RPC: env-tunable deadline, bounded
  exponential backoff, per-op retry counter, retries=0 passthrough;
- doctor attribution: the ``migration`` bucket still sums EXACTLY to
  delta_ms; fold totals (migrate_seconds/bytes, migrated_requests);
- the ``serving_fleet_migration_predicted`` anchor + bench_compare map;
- router ``migration_target`` policy (pure) and the
  ``pause_replica``/``resume_replica`` fault-injection helpers;
- one REAL 2-replica fleet (replica processes): mid-stream live
  migration (chunked, checksummed, warm-dest prefix reuse), SIGKILL
  failover that replays only the suffix the surviving cache misses,
  and drain-by-migrate scale-in — zero failed requests, token-exact
  vs. the single-replica oracle throughout;
- a slow-marked chaos loop: kill -> migrate -> scale-in cycles under
  sustained load (plus a SIGSTOP straggler shed) with zero failures.
"""
import json
import signal
import socket
import threading
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models.gpt import gpt_tiny_config
from paddle_tpu.serving import (ContinuousBatchingScheduler, PagePool,
                                PagePoolError, ServingEngine)
from paddle_tpu.serving.router import PrefixAffinityRouter


def _fleet_cfg():
    return gpt_tiny_config(num_layers=2, hidden_size=32, num_heads=2,
                           max_position_embeddings=64)


ENGINE_KW = dict(page_size=8, decode_buckets=(1, 2, 4, 8),
                 prefill_chunk=8, prefix_cache=True)


def _tiny_model(seed=0):
    from paddle_tpu.models.gpt import GPTForPretraining, GPTModel
    paddle.seed(seed)
    cfg = gpt_tiny_config()
    return GPTForPretraining(GPTModel(cfg)), cfg


def _oracle(model):
    from paddle_tpu.models.gpt import GPTGenerator
    gen = GPTGenerator(model, temperature=0.0)

    def ref(p, n):
        full = np.asarray(gen(p[None, :], max_new_tokens=n)._value)[0]
        return [int(t) for t in full[len(p):]]
    return ref


def _drain_env(monkeypatch):
    monkeypatch.delenv("PADDLE_TELEMETRY_DIR", raising=False)
    monkeypatch.delenv("PADDLE_REQUESTS_PER_RANK", raising=False)


# ===========================================================================
# pool: token_rows
# ===========================================================================

def test_pool_token_rows_maps_positions_to_page_rows():
    pool = PagePool(num_pages=9, page_size=4, num_layers=2,
                    num_kv_heads=2, head_dim=8)
    pages = pool.alloc("a", 10)                   # 3 pages
    rows = pool.token_rows("a", 0, 10)
    assert rows.dtype == np.int32 and rows.shape == (10,)
    # row i lives in page pages[i // ps] at slot i % ps
    for i, r in enumerate(rows):
        assert r == pages[i // 4] * 4 + i % 4
    # suffix window
    np.testing.assert_array_equal(pool.token_rows("a", 8, 10), rows[8:])
    assert pool.token_rows("a", 4, 4).shape == (0,)
    with pytest.raises(PagePoolError):
        pool.token_rows("a", 0, 11)               # beyond seq_len
    with pytest.raises(PagePoolError):
        pool.token_rows("a", -1, 4)
    with pytest.raises(PagePoolError):
        pool.token_rows("nope", 0, 1)


# ===========================================================================
# in-process scheduler roundtrips (token-exact vs. oracle)
# ===========================================================================

def _mk(model, prefix_cache=False):
    eng = ServingEngine(model, page_size=8, decode_buckets=(1, 2, 4),
                        aot=False, prefix_cache=prefix_cache)
    return ContinuousBatchingScheduler(eng), eng


def _step_to_mid_decode(sched, r, min_tokens=3):
    for _ in range(300):
        if r.state == "running" and len(r.tokens) >= min_tokens \
                and not r.done:
            return
        sched.step()
    pytest.fail(f"request never reached mid-decode: {r.state}")


def test_migration_roundtrip_cold_dest_token_exact():
    model, cfg = _tiny_model()
    ref = _oracle(model)
    src, src_eng = _mk(model)
    dst, dst_eng = _mk(model)
    rng = np.random.default_rng(1)
    p = rng.integers(0, cfg.vocab_size, (13,)).astype(np.int32)
    r = src.submit(p, max_new_tokens=10, rid=101)
    _step_to_mid_decode(src, r)
    assert src.migratable_rids() == [101]

    ck = src.checkpoint_request(101)
    assert ck is not None and r.state == "migrating"
    assert src.status()["migrating_out"] == 1
    assert src.checkpoint_request(101) is None     # not running anymore
    token_ids = ck["prompt"] + ck["tokens"][:-1]
    ok, cached = dst.prepare_migration_in(101, token_ids,
                                          len(ck["prompt"]), ck["max_new"])
    assert ok is True and cached == 0              # no cache: cold transfer
    k, v = src_eng.export_kv(101, start=cached)
    assert k.shape == v.shape
    assert k.shape[1] == len(token_ids)            # every valid KV row moved
    meta = dict(ck, migrate_bytes=k.nbytes + v.nbytes,
                migrate_s=ck["migrate_s"] + 0.002, migrate_window_s=0.002)
    ok2, cached2 = dst.adopt_migrated(meta, k, v)
    assert ok2 is True and cached2 == 0
    src.complete_migration(101)
    assert src.status()["migrations_out"] == 1
    assert src_eng.pool.pages_in_use == 0          # source fully released

    fin = dst.run()
    assert [q.rid for q in fin] == [101] and fin[0].state == "finished"
    assert fin[0].tokens == ref(p, 10)             # token-exact resume
    s = fin[0].summary()
    assert s["migrations"] == 1 and s["migrate_bytes"] == k.nbytes + v.nbytes
    assert dst.status()["migrations_in"] == 1
    assert dst_eng.kv_migrations_in == 1
    assert dst_eng.status()["migration"]["kv_bytes"] > 0
    assert dst_eng.pool.pages_in_use == 0 and dst._reserved_pages == 0


def test_migration_warm_dest_transfers_suffix_only():
    model, cfg = _tiny_model(seed=2)
    ref = _oracle(model)
    src, src_eng = _mk(model)
    dst, dst_eng = _mk(model, prefix_cache=True)
    rng = np.random.default_rng(2)
    p = rng.integers(0, cfg.vocab_size, (17,)).astype(np.int32)
    # destination already served the same prompt: its radix cache holds
    # the prefix (greedy + same weights => identical decode path)
    warm = dst.submit(p, max_new_tokens=6, rid=7)
    dst.run()
    assert warm.state == "finished"

    r = src.submit(p, max_new_tokens=6, rid=8)
    _step_to_mid_decode(src, r, min_tokens=2)
    ck = src.checkpoint_request(8)
    token_ids = ck["prompt"] + ck["tokens"][:-1]
    ok, cached = dst.prepare_migration_in(8, token_ids, len(ck["prompt"]),
                                          ck["max_new"])
    # page-granular prefix reuse: at least one full page is NOT resent
    assert ok is True and cached >= 8 and cached % 8 == 0
    assert cached < len(token_ids)
    k, v = src_eng.export_kv(8, start=cached)
    assert k.shape[1] == len(token_ids) - cached   # uncached suffix only
    ok2, cached2 = dst.adopt_migrated(
        dict(ck, migrate_bytes=k.nbytes + v.nbytes), k, v)
    assert ok2 is True and cached2 == cached
    src.complete_migration(8)

    fin = {q.rid: q for q in dst.run()}
    assert fin[8].state == "finished" and fin[8].tokens == ref(p, 6)
    assert fin[8].tokens == fin[7].tokens          # same greedy stream
    assert dst._reserved_pages == 0


def test_migration_abort_source_stays_authoritative():
    model, cfg = _tiny_model(seed=3)
    ref = _oracle(model)
    src, src_eng = _mk(model)
    rng = np.random.default_rng(3)
    p = rng.integers(0, cfg.vocab_size, (9,)).astype(np.int32)
    r = src.submit(p, max_new_tokens=8, rid=11)
    _step_to_mid_decode(src, r)
    assert src.checkpoint_request(11) is not None
    # transfer failed: restore the checkpoint, resume exactly in place
    assert src.abort_migration(11) is True
    assert src.abort_migration(11) is False        # idempotent
    fin = src.run()
    assert fin[0].tokens == ref(p, 8)
    assert src_eng.pool.pages_in_use == 0
    assert src.status()["migrations_out"] == 0


def test_migration_in_abort_and_corrupt_payload_restore_reservations():
    model, cfg = _tiny_model(seed=4)
    ref = _oracle(model)
    src, src_eng = _mk(model)
    dst, dst_eng = _mk(model)
    rng = np.random.default_rng(4)
    p = rng.integers(0, cfg.vocab_size, (11,)).astype(np.int32)
    r = src.submit(p, max_new_tokens=7, rid=21)
    _step_to_mid_decode(src, r)
    ck = src.checkpoint_request(21)
    token_ids = ck["prompt"] + ck["tokens"][:-1]

    # staged then aborted: reservation + staged import fully unwound
    base = dst._reserved_pages
    ok, _ = dst.prepare_migration_in(21, token_ids, len(ck["prompt"]),
                                     ck["max_new"])
    assert ok and dst._reserved_pages > base
    assert dst.abort_migration_in(21) is True
    assert dst.abort_migration_in(21) is False
    assert dst._reserved_pages == base and not dst_eng._kv_import

    # corrupt payload (wrong row count): rejected, reservation restored,
    # and a fresh begin starts clean afterwards
    ok, cached = dst.prepare_migration_in(21, token_ids, len(ck["prompt"]),
                                          ck["max_new"])
    assert ok is True
    k, v = src_eng.export_kv(21, start=cached)
    bad, reason = dst.adopt_migrated(dict(ck), k[:, :-1], v[:, :-1])
    assert bad is False and "payload" in reason
    assert dst._reserved_pages == base and not dst_eng._kv_import
    assert dst_eng.pool.pages_in_use == 0

    ok, cached = dst.prepare_migration_in(21, token_ids, len(ck["prompt"]),
                                          ck["max_new"])
    assert ok is True
    ok2, _ = dst.adopt_migrated(
        dict(ck, migrate_bytes=k.nbytes + v.nbytes), k, v)
    assert ok2 is True
    src.complete_migration(21)
    fin = dst.run()
    assert fin[0].rid == 21 and fin[0].tokens == ref(p, 7)
    # an unknown rid is refused, not crashed
    assert dst.adopt_migrated(dict(ck, rid=999), k, v) \
        == (False, "no_staged_migration")


def test_prepare_migration_in_admission_reasons():
    from paddle_tpu.serving.scheduler import _ShapeProbeEngine
    eng = _ShapeProbeEngine(decode_buckets=(1, 2), prefill_buckets=(8, 32),
                            page_size=8, num_pages=32, max_seq_len=32)
    sched = ContinuousBatchingScheduler(eng)
    # a device-free probe engine has no KV import surface
    assert sched.prepare_migration_in(1, [1, 2, 3], 3, 4) \
        == (False, "engine_unsupported")

    model, cfg = _tiny_model(seed=5)
    dst, _ = _mk(model)
    toks = list(range(8))
    dst.drain()
    assert dst.prepare_migration_in(1, toks, 8, 4) == (False, "draining")
    dst.draining = False
    assert dst.prepare_migration_in(1, toks, 8, 999)[1] == "too_long"
    ok, _ = dst.prepare_migration_in(1, toks, 8, 4)
    assert ok is True
    assert dst.prepare_migration_in(1, toks, 8, 4) \
        == (False, "duplicate_rid")
    dst.abort_migration_in(1)


# ===========================================================================
# hardened control-plane RPC
# ===========================================================================

def test_rpc_retry_backoff_counter_and_retries_zero(monkeypatch):
    from paddle_tpu.observability import instrument as obs
    from paddle_tpu.serving.fleet import _rpc_request
    monkeypatch.setenv("PADDLE_FLEET_RPC_RETRY_BASE_S", "0.001")
    state = {"fail": 2}
    srv = socket.create_server(("127.0.0.1", 0))
    addr = srv.getsockname()[:2]

    def serve():
        while True:
            try:
                conn, _ = srv.accept()
            except OSError:
                return
            with conn:
                if state["fail"] > 0:
                    state["fail"] -= 1
                    continue                    # slam the door: OSError
                with conn.makefile("rb") as f:
                    msg = json.loads(f.readline().decode())
                conn.sendall(json.dumps(
                    {"ok": True, "echo": msg["op"]}).encode() + b"\n")

    threading.Thread(target=serve, daemon=True).start()
    try:
        c = obs.fleet_rpc_retries_counter().labels(op="ping")
        before = c.value
        t0 = time.monotonic()
        reply = _rpc_request(addr, {"op": "ping"}, timeout=5.0, retries=3)
        assert reply == {"ok": True, "echo": "ping"}
        assert c.value == before + 2            # one inc per retry, by op
        # backoff floor: 0.001*1 + 0.001*2 (jitter can only add)
        assert time.monotonic() - t0 >= 0.003
        # non-replayable ops opt out: first transient error surfaces
        state["fail"] = 1
        with pytest.raises(OSError):
            _rpc_request(addr, {"op": "poll"}, timeout=5.0, retries=0)
        assert c.value == before + 2            # no retry, no inc
        # retry budget exhausted -> the error still surfaces
        state["fail"] = 99
        with pytest.raises(OSError):
            _rpc_request(addr, {"op": "ping"}, timeout=5.0, retries=1)
    finally:
        srv.close()


def test_chunk_blob_respects_env_size(monkeypatch):
    from paddle_tpu.serving.fleet import _chunk_blob
    monkeypatch.setenv("PADDLE_FLEET_MIGRATE_CHUNK_BYTES", "4")
    blob = b"0123456789"
    chunks = _chunk_blob(blob)
    assert chunks == [b"0123", b"4567", b"89"]
    assert b"".join(chunks) == blob
    monkeypatch.setenv("PADDLE_FLEET_MIGRATE_CHUNK_BYTES", "0")
    assert len(_chunk_blob(blob)) == len(blob)   # floor of 1 byte


# ===========================================================================
# doctor / fold: the migration bucket sums exactly
# ===========================================================================

def _fleet_records(migrated=0):
    recs = []
    for rank, mean in ((0, 0.010), (1, 0.030)):
        for i in range(3):
            recs.append({
                "event": "request", "rank": rank, "rid": rank * 3 + i,
                "state": "finished", "new_tokens": 8,
                "router_wait_s": 0.05, "queue_wait_s": 0.01,
                "prefill_s": 0.02, "decode_s": mean * 7,
                "ttft_s": 0.031, "total_s": 0.031 + mean * 7,
                "per_token_s": {"count": 8, "mean": mean, "p50": mean,
                                "p95": mean, "p99": mean, "max": mean},
            })
    for r in recs[:migrated]:
        r.update(migrations=1, migrate_s=0.024, migrate_bytes=4096)
    return recs


def test_fold_migration_totals():
    from paddle_tpu.observability.reqtrace import fold_request_records
    sv = fold_request_records(_fleet_records(migrated=2))
    assert sv["migrate_seconds_total"] == pytest.approx(0.048)
    assert sv["migrate_bytes_total"] == 8192
    assert sv["migrated_requests"] == 2
    clean = fold_request_records(_fleet_records())
    assert clean["migrate_seconds_total"] == 0.0
    assert clean["migrated_requests"] == 0


def test_doctor_migration_bucket_sums_exactly_to_delta():
    from paddle_tpu.observability.doctor import attribute_serving_gap
    from paddle_tpu.observability.reqtrace import fold_request_records
    pred = {"predicted_decode_step_ms": 5.0,
            "predicted_per_token_ms_p50": 5.0}
    summary = {"serving": fold_request_records(_fleet_records(migrated=2)),
               "compile": {"seconds": 0.48}}
    attr = attribute_serving_gap(summary, pred)
    # 2 x 24ms over 48 tokens = 1ms/token carved out of the residual
    assert attr["buckets"]["migration"] == pytest.approx(
        0.048 / 48 * 1e3, abs=1e-6)
    assert "router_queue" in attr["buckets"]
    assert sum(attr["buckets"].values()) == pytest.approx(
        attr["delta_ms"], abs=1e-6)
    # no migrations -> no bucket (classic shape preserved)
    attr0 = attribute_serving_gap(
        {"serving": fold_request_records(_fleet_records())}, pred)
    assert "migration" not in attr0["buckets"]
    assert sum(attr0["buckets"].values()) == pytest.approx(
        attr0["delta_ms"], abs=1e-6)


# ===========================================================================
# predicted anchor + bench_compare mapping
# ===========================================================================

def test_predicted_migration_row_payload_and_speedup():
    from paddle_tpu.serving.predict import predicted_migration_row
    row = predicted_migration_row("tiny", prompt_len=64, decoded=8,
                                  cached_fraction=0.5, prefill_chunk=16,
                                  page_size=16)
    # cached prefix is page-aligned: 32 of 64 prompt tokens reused
    assert row["cached_prefix_len"] == 32
    assert row["payload_tokens"] == 64 + 8 - 32
    assert row["predicted_payload_mb"] < row["predicted_full_kv_mb"]
    # migrating beats a cold full-prompt replay, on ICI and (less so) DCN
    assert row["predicted_speedup"] > 1.0
    assert row["predicted_speedup"] >= row["predicted_speedup_dcn"] > 0
    assert row["predicted_migration_ms"] < row["predicted_replay_ms"]
    assert row["dcn_bw_assumption"] == "ici_bw/8"
    # less destination reuse -> bigger payload -> smaller win
    cold = predicted_migration_row("tiny", prompt_len=64, decoded=8,
                                   cached_fraction=0.0, prefill_chunk=16,
                                   page_size=16)
    assert cold["cached_prefix_len"] == 0
    assert cold["payload_tokens"] == 72
    assert cold["predicted_speedup"] <= row["predicted_speedup"]
    # at least one KV row always travels even at cached_fraction=1
    full = predicted_migration_row("tiny", prompt_len=64, decoded=1,
                                   cached_fraction=1.0, prefill_chunk=16,
                                   page_size=16)
    assert full["payload_tokens"] >= 1


def test_bench_compare_anchors_migration_row():
    from tools.bench_compare import _ANCHOR_MAP, _predicted_anchor
    assert _ANCHOR_MAP["serving_fleet_migration"] \
        == "serving_fleet_migration_predicted"
    rows = {"serving_fleet_migration_predicted":
            {"metric": "serving_fleet_migration_predicted", "value": 3.0}}
    assert _predicted_anchor("serving_fleet_migration_ms", rows) \
        is rows["serving_fleet_migration_predicted"]


# ===========================================================================
# router policy + fault injection helpers (pure)
# ===========================================================================

def _snap(**kw):
    d = {"healthy": True, "draining": False, "queue_depth": 0,
         "pending": 0, "free_pages": 50, "num_pages": 64}
    d.update(kw)
    return d


def test_migration_target_policy():
    r = PrefixAffinityRouter(max_queue_depth=4)
    snaps = {0: _snap(pending=3), 1: _snap(pending=1),
             2: _snap(draining=True), 3: _snap(healthy=False)}
    assert r.migration_target(snaps) == 1           # least-loaded healthy
    assert r.migration_target(snaps, exclude=(1,)) == 0
    assert r.migration_target(snaps, exclude=(0, 1)) is None
    # saturated (queue at cap) loses to a loaded-but-open peer
    snaps2 = {0: _snap(queue_depth=4), 1: _snap(pending=5)}
    assert r.migration_target(snaps2) == 1
    # everyone saturated: least-loaded of the bad set, never None
    snaps3 = {0: _snap(queue_depth=4, pending=9), 1: _snap(queue_depth=4)}
    assert r.migration_target(snaps3) == 1
    # page pressure with a queue in front counts as saturation
    snaps4 = {0: _snap(free_pages=1, queue_depth=1), 1: _snap(pending=7)}
    assert r.migration_target(snaps4, pages_needed=6) == 1


def test_pause_resume_replica_delegate_signals():
    from paddle_tpu.distributed.fleet.elastic import (pause_replica,
                                                      resume_replica)

    class _FakeRouter:
        def __init__(self):
            self.calls = []

        def kill_replica(self, rid, sig=signal.SIGKILL):
            self.calls.append((rid, sig))
            return 4242

    r = _FakeRouter()
    assert pause_replica(r, 1) == 4242
    assert resume_replica(r, 2) == 4242
    assert r.calls == [(1, signal.SIGSTOP), (2, signal.SIGCONT)]


# ===========================================================================
# real fleet: live migration + SIGKILL failover + drain-by-migrate
# ===========================================================================

def _shared_prompts(cfg, n, rng, prefix_len=12, suffix_len=4):
    prefix = rng.integers(0, cfg.vocab_size, (prefix_len,)).astype(np.int32)
    return [np.concatenate(
        [prefix, rng.integers(0, cfg.vocab_size,
                              (suffix_len,)).astype(np.int32)])
        for _ in range(n)]


def test_fleet_live_migration_failover_and_drain_by_migrate(
        tmp_path, monkeypatch):
    """ACCEPTANCE: one real 2-replica fleet. (1) a mid-stream request
    live-migrates (chunked + checksummed; only the suffix the warm
    destination cache misses travels) and resumes TOKEN-EXACT; (2) a
    SIGKILLed replica's in-flight work replays only the suffix the
    surviving prefix cache misses; (3) scale-in drains by migrating.
    Zero failed requests; every output identical to the single-replica
    greedy oracle; /status + federation surface the migration counts."""
    from paddle_tpu.distributed.fleet.elastic.fault_injection import \
        kill_replica
    from paddle_tpu.models.gpt import GPTForPretraining, GPTModel
    from paddle_tpu.serving.fleet import FleetRouter
    _drain_env(monkeypatch)
    # force multi-chunk streaming on tiny payloads (replicas inherit env)
    monkeypatch.setenv("PADDLE_FLEET_MIGRATE_CHUNK_BYTES", "2048")
    cfg = _fleet_cfg()
    paddle.seed(7)
    model = GPTForPretraining(GPTModel(cfg))
    ref = _oracle(model)
    ckpt = str(tmp_path / "gpt.pdparams")
    paddle.save(model.state_dict(), ckpt)
    rng = np.random.default_rng(5)
    prompts = _shared_prompts(cfg, 12, rng)
    ps = ENGINE_KW["page_size"]

    # round_robin so BOTH replicas warm the shared prefix in phase 0
    fleet = FleetRouter(cfg, checkpoint=ckpt, n_replicas=2,
                        policy="round_robin",
                        engine_kwargs=dict(ENGINE_KW),
                        run_dir=str(tmp_path / "run"), seed=7,
                        max_restarts=1)
    expected = {}

    def submit(p, n):
        rid = fleet.submit(p, max_new_tokens=n)
        expected[rid] = (p, n)
        return rid

    try:
        fleet.start()
        # ---- phase 0: warm both replica caches with the shared prefix
        for p in prompts[:4]:
            submit(p, 4)
        assert fleet.run(timeout=240)

        # ---- phase 1: live-migrate a mid-decode request
        mig_rid, rep = None, None
        for _attempt in range(6):
            rid = submit(prompts[4], 32)
            deadline = time.monotonic() + 90
            while rid not in fleet.results \
                    and time.monotonic() < deadline:
                fleet.tick()
                r2 = fleet.migrate(rid)
                if r2.get("migrated"):
                    mig_rid, rep = rid, r2
                    break
                time.sleep(0.005)
            if mig_rid is not None:
                break
            assert rid in fleet.results    # finished too fast; try again
        assert mig_rid is not None, "could not catch a request mid-decode"
        assert fleet.run(timeout=240)
        assert rep["bytes"] > 0 and rep["chunks"] >= 2
        # warm destination: at least one full page was NOT resent
        assert rep["cached_len"] >= ps
        assert rep["payload_tokens"] < len(prompts[4]) + 32
        res = fleet.results[mig_rid]
        assert res["state"] == "finished" and res["replica"] == rep["to"]
        summ = res["summary"]
        assert summ["migrations"] == 1
        assert summ["migrate_bytes"] == rep["bytes"]
        assert summ["migrate_s"] > 0
        assert fleet.migrations_completed >= 1
        assert mig_rid in fleet.migrated_rids
        st = fleet.fleet_status()["migrations"]
        assert st["completed"] >= 1 and st["bytes"] > 0 and st["recent"]

        # ---- phase 2: SIGKILL failover replays only the uncached suffix
        for p in prompts[5:11]:
            submit(p, 8)
        killed = None
        deadline = time.monotonic() + 240
        while killed is None and time.monotonic() < deadline:
            fleet.tick()
            target = next(
                (rec["replica"] for rec in fleet._inflight.values()
                 if rec.get("replica") is not None), None)
            if target is not None:
                kill_replica(fleet, target)
                killed = target
            time.sleep(0.005)
        assert killed is not None
        assert fleet.run(timeout=240)
        assert fleet.requeued_rids          # work WAS in flight
        for rid in set(fleet.requeued_rids):
            s = fleet.results[rid]
            assert s["state"] == "finished"
            # zero cached prefill work replayed: the surviving cache
            # covers the shared prefix, so the re-prefill is suffix-only
            # (strictly fewer replayed tokens than a full-prompt replay)
            assert s["summary"]["cached_prefix_len"] >= ps

        # ---- phase 3: drain-by-migrate scale-in
        deadline = time.monotonic() + 180
        while time.monotonic() < deadline and len(
                [h for h in fleet.replicas.values()
                 if h.alive() and not h.retired]) < 2:
            fleet.tick()
            time.sleep(0.05)     # wait for the relaunched replacement
        before_mig = fleet.migrations_completed
        drained = False
        for attempt in range(3):   # slow boxes: decode can outrun the drain
            for i in range(3):
                submit(prompts[(5 + attempt * 3 + i) % len(prompts)], 40)
            victim = None
            deadline = time.monotonic() + 90
            while victim is None and time.monotonic() < deadline:
                fleet.tick()
                for rid_, h in fleet.replicas.items():
                    if getattr(h, "retired", False):
                        continue
                    if int((h.last_status or {}).get("running") or 0) > 0:
                        victim = rid_
                        break
                time.sleep(0.005)
            assert victim is not None
            assert fleet.scale_in(victim, reason="test") == victim
            assert fleet.run(timeout=240)
            deadline = time.monotonic() + 120
            while victim in fleet.replicas and time.monotonic() < deadline:
                fleet.tick()
                time.sleep(0.05)
            assert victim not in fleet.replicas
            if fleet.migrations_completed > before_mig:
                drained = True
                break
            # the victim's work finished before a migration could land;
            # restore two-replica capacity and try again with fresh work
            fleet.scale_out(reason="test_retry")
            deadline = time.monotonic() + 180
            while time.monotonic() < deadline and len(
                    [h for h in fleet.replicas.values()
                     if h.alive() and not h.retired]) < 2:
                fleet.tick()
                time.sleep(0.05)
        # the retiring replica's running work MOVED, not re-ran
        assert drained, "scale-in never migrated running work off the victim"

        # ---- every request finished, token-exact vs. the oracle
        for rid, (p, n) in expected.items():
            res = fleet.results[rid]
            assert res["state"] == "finished", (rid, res)
            assert res["tokens"] == ref(p, n), f"rid {rid} diverged"
        summary = fleet.shutdown()
    finally:
        fleet.shutdown(federate=False)
    sv = summary["serving"]
    assert sv["migrated_requests"] >= 1
    assert sv["migrate_seconds_total"] > 0
    assert sv["migrate_bytes_total"] > 0
    fm = summary["fleet"]["migrations"]
    assert fm["completed"] >= 2 and fm["failed"] >= 0
    assert fm["bytes"] > 0 and mig_rid in fm["migrated_rids"]


@pytest.mark.slow
def test_fleet_chaos_kill_migrate_scale_cycles_zero_failed(
        tmp_path, monkeypatch):
    """Chaos loop: kill -> migrate -> scale-in cycles (plus a SIGSTOP
    straggler that gets shed) under sustained load. Zero failed
    requests, no stuck scheduler/pool state on any survivor, and every
    greedy output identical to the single-replica oracle."""
    from paddle_tpu.distributed.fleet.elastic.fault_injection import (
        kill_replica, pause_replica, resume_replica)
    from paddle_tpu.models.gpt import GPTForPretraining, GPTModel
    from paddle_tpu.serving.fleet import FleetRouter
    _drain_env(monkeypatch)
    monkeypatch.setenv("PADDLE_FLEET_MIGRATE_CHUNK_BYTES", "4096")
    monkeypatch.setenv("PADDLE_FLEET_POLL_TIMEOUT_S", "1")
    monkeypatch.setenv("PADDLE_FLEET_STRAGGLER_POLLS", "2")
    cfg = _fleet_cfg()
    paddle.seed(13)
    model = GPTForPretraining(GPTModel(cfg))
    ref = _oracle(model)
    ckpt = str(tmp_path / "gpt.pdparams")
    paddle.save(model.state_dict(), ckpt)
    rng = np.random.default_rng(9)
    prompts = _shared_prompts(cfg, 8, rng)

    fleet = FleetRouter(cfg, checkpoint=ckpt, n_replicas=2,
                        policy="round_robin",
                        engine_kwargs=dict(ENGINE_KW),
                        run_dir=str(tmp_path / "run"), seed=13,
                        max_restarts=6)
    expected = {}

    def submit_batch(n_new):
        for i in range(n_new):
            p = prompts[i % len(prompts)]
            rid = fleet.submit(p, max_new_tokens=12)
            expected[rid] = (p, 12)

    def live_replicas():
        return [r for r, h in fleet.replicas.items()
                if h.alive() and not h.retired and not h.draining]

    try:
        fleet.start()
        submit_batch(4)
        assert fleet.run(timeout=240)      # warm both caches
        for cycle in range(2):
            # kill a loaded replica
            submit_batch(5)
            deadline = time.monotonic() + 240
            killed = None
            while killed is None and time.monotonic() < deadline:
                fleet.tick()
                target = next(
                    (rec["replica"] for rec in fleet._inflight.values()
                     if rec.get("replica") is not None), None)
                if target is not None:
                    kill_replica(fleet, target)
                    killed = target
                time.sleep(0.005)
            assert killed is not None
            assert fleet.run(timeout=300)
            # best-effort live migration of a fresh mid-decode request
            deadline = time.monotonic() + 180
            while len(live_replicas()) < 2 \
                    and time.monotonic() < deadline:
                fleet.tick()
                time.sleep(0.05)
            rid = fleet.submit(prompts[cycle], max_new_tokens=24)
            expected[rid] = (prompts[cycle], 24)
            deadline = time.monotonic() + 90
            while rid not in fleet.results \
                    and time.monotonic() < deadline:
                fleet.tick()
                if fleet.migrate(rid).get("migrated"):
                    break
                time.sleep(0.005)
            assert fleet.run(timeout=240)
            # scale-in (drain-by-migrate) then restore the pair
            if len(live_replicas()) >= 2:
                submit_batch(3)
                retired = fleet.scale_in(reason="chaos")
                assert retired is not None
                assert fleet.run(timeout=300)
                deadline = time.monotonic() + 120
                while retired in fleet.replicas \
                        and time.monotonic() < deadline:
                    fleet.tick()
                    time.sleep(0.05)
                assert retired not in fleet.replicas
            if len(live_replicas()) < 2:
                fleet.scale_out(reason="chaos")
        # straggler: SIGSTOP one replica under load; supervision sheds
        # its in-flight work after consecutive poll misses, SIGCONT
        # makes any duplicate completion harmless (rid idempotency)
        deadline = time.monotonic() + 180
        while len(live_replicas()) < 2 and time.monotonic() < deadline:
            fleet.tick()
            time.sleep(0.05)
        if len(live_replicas()) >= 2:
            submit_batch(4)
            fleet.tick()
            wedged = live_replicas()[0]
            pause_replica(fleet, wedged)
            deadline = time.monotonic() + 60
            while not fleet.shed_events \
                    and time.monotonic() < deadline:
                fleet.tick()
                time.sleep(0.05)
            resume_replica(fleet, wedged)
            assert fleet.shed_events
            assert fleet.shed_events[-1]["reason"] == "wedged"
            assert fleet.run(timeout=300)

        # zero failed requests, token-exact vs. the oracle
        assert len(fleet.results) >= len(expected)
        for rid, (p, n) in expected.items():
            res = fleet.results[rid]
            assert res["state"] == "finished", (rid, res)
            assert res["tokens"] == ref(p, n)
        # no stuck migration/scheduler state or leaked work anywhere
        fleet.tick()
        for h in fleet.replicas.values():
            st = h.last_status or {}
            if not st:
                continue
            assert st.get("queue_depth") == 0
            assert st.get("running") == 0 and st.get("prefilling") == 0
            assert st.get("migrating_out") == 0
            assert st.get("migrating_in") == 0
        fleet.shutdown()
    finally:
        fleet.shutdown(federate=False)
