"""GPT model tests: eager forward/loss, and the compiled hybrid train step
(pp×dp×mp GPipe shard_map) against the eager single-device oracle."""
import numpy as np
import pytest
import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.distributed import mesh as mesh_mod
from paddle_tpu.distributed.mesh import HybridCommunicateGroup
from paddle_tpu.models.gpt import (
    GPTForPretraining, GPTHybridTrainStep, GPTModel, GPTPretrainingCriterion,
    gpt_tiny_config,
)


@pytest.fixture(autouse=True)
def reset_mesh():
    saved = (mesh_mod._global_mesh, mesh_mod._hcg)
    yield
    mesh_mod._global_mesh, mesh_mod._hcg = saved


def _batch(cfg, b, s, seed=0):
    rng = np.random.default_rng(seed)
    ids = rng.integers(0, cfg.vocab_size, size=(b, s)).astype(np.int32)
    labels = np.roll(ids, -1, axis=1).astype(np.int32)
    return ids, labels


def test_gpt_eager_forward_and_loss():
    mesh_mod._global_mesh, mesh_mod._hcg = None, None
    cfg = gpt_tiny_config()
    model = GPTForPretraining(GPTModel(cfg))
    crit = GPTPretrainingCriterion()
    ids, labels = _batch(cfg, 2, 16)
    logits = model(paddle.to_tensor(ids))
    assert logits.shape == [2, 16, cfg.vocab_size]
    loss = crit(logits, paddle.to_tensor(labels))
    # random init -> loss near ln(vocab)
    assert abs(float(loss.numpy()) - np.log(cfg.vocab_size)) < 1.0
    loss.backward()
    wte = model.gpt.embeddings.word_embeddings
    assert wte.grad is not None and np.abs(wte.grad.numpy()).max() > 0


def test_gpt_hybrid_step_loss_matches_eager():
    """Step-1 loss of the compiled pp2×mp2×dp2 GPipe program == eager loss."""
    mesh_mod._global_mesh, mesh_mod._hcg = None, None
    cfg = gpt_tiny_config()
    model = GPTForPretraining(GPTModel(cfg))
    crit = GPTPretrainingCriterion()
    ids, labels = _batch(cfg, 4, 16, seed=1)

    logits = model(paddle.to_tensor(ids))
    ref = float(crit(logits, paddle.to_tensor(labels)).numpy())

    hcg = HybridCommunicateGroup(dp_degree=2, mp_degree=2, pp_degree=2)
    step = GPTHybridTrainStep(model, cfg, hcg, n_micro=2, lr=1e-3,
                              remat=False)
    loss = float(step(ids, labels).numpy())
    np.testing.assert_allclose(loss, ref, rtol=2e-4, atol=2e-4)


def test_gpt_hybrid_step_trains():
    mesh_mod._global_mesh, mesh_mod._hcg = None, None
    cfg = gpt_tiny_config()
    model = GPTForPretraining(GPTModel(cfg))
    hcg = HybridCommunicateGroup(dp_degree=2, mp_degree=2, pp_degree=2)
    step = GPTHybridTrainStep(model, cfg, hcg, n_micro=2, lr=3e-3)
    ids, labels = _batch(cfg, 4, 16, seed=2)
    losses = [float(step(ids, labels).numpy()) for _ in range(8)]
    assert losses[-1] < losses[0], losses
    # params really live pp/mp-sharded on the mesh
    spec = step.params["blocks"]["wqkv"].sharding.spec
    assert "pp" in spec and any("mp" in (s or ()) for s in spec)


def test_gpt_virtual_pipeline_matches_oracle():
    """pp=2 x virtual_pp_degree=2 (interleave parity: pp_layers.py:520)
    must track the pp=1 oracle step-for-step, including the chunk
    permutation of the stacked layer params."""
    cfg = gpt_tiny_config()  # 4 layers -> 2 stages x 2 chunks x 1 layer
    rng = np.random.default_rng(7)
    ids = rng.integers(0, cfg.vocab_size, size=(4, 16)).astype(np.int32)
    labels = np.roll(ids, -1, axis=1).astype(np.int32)

    losses = {}
    for pp, vpp in ((1, 1), (2, 2)):
        mesh_mod._global_mesh, mesh_mod._hcg = None, None
        paddle.seed(123)
        hcg = HybridCommunicateGroup(dp_degree=1, mp_degree=1,
                                     pp_degree=pp)
        model = GPTForPretraining(GPTModel(cfg))
        step = GPTHybridTrainStep(model, cfg, hcg, n_micro=2, lr=1e-3,
                                  virtual_pp_degree=vpp)
        losses[(pp, vpp)] = [float(step(ids, labels).numpy())
                             for _ in range(3)]
    np.testing.assert_allclose(losses[(2, 2)], losses[(1, 1)], rtol=1e-5)


def test_gpt_virtual_pipeline_scan_path_matches_oracle(monkeypatch):
    """Force the lax.scan tick rounds (long-schedule fallback) by dropping
    the unroll threshold; numerics must still track the oracle."""
    from paddle_tpu.models import gpt as gpt_mod
    cfg = gpt_tiny_config()
    rng = np.random.default_rng(8)
    ids = rng.integers(0, cfg.vocab_size, size=(4, 16)).astype(np.int32)
    labels = np.roll(ids, -1, axis=1).astype(np.int32)

    mesh_mod._global_mesh, mesh_mod._hcg = None, None
    paddle.seed(321)
    hcg = HybridCommunicateGroup(dp_degree=1, mp_degree=1, pp_degree=1)
    model = GPTForPretraining(GPTModel(cfg))
    oracle = GPTHybridTrainStep(model, cfg, hcg, n_micro=2, lr=1e-3)
    want = [float(oracle(ids, labels).numpy()) for _ in range(2)]

    monkeypatch.setattr(gpt_mod, "_UNROLL_TICKS", 0)
    mesh_mod._global_mesh, mesh_mod._hcg = None, None
    paddle.seed(321)
    hcg2 = HybridCommunicateGroup(dp_degree=1, mp_degree=1, pp_degree=2)
    model2 = GPTForPretraining(GPTModel(cfg))
    step2 = GPTHybridTrainStep(model2, cfg, hcg2, n_micro=2, lr=1e-3,
                               virtual_pp_degree=2)
    got = [float(step2(ids, labels).numpy()) for _ in range(2)]
    np.testing.assert_allclose(got, want, rtol=1e-5)


@pytest.mark.slow
def test_gpt_hybrid_remat_matches_noremat():
    mesh_mod._global_mesh, mesh_mod._hcg = None, None
    cfg = gpt_tiny_config()
    model = GPTForPretraining(GPTModel(cfg))
    ids, labels = _batch(cfg, 4, 16, seed=3)

    hcg = HybridCommunicateGroup(dp_degree=1, mp_degree=1, pp_degree=4)
    s1 = GPTHybridTrainStep(model, cfg, hcg, n_micro=4, remat=False)
    s2 = GPTHybridTrainStep(model, cfg, hcg, n_micro=4, remat=True)
    l1 = float(s1(ids, labels).numpy())
    l2 = float(s2(ids, labels).numpy())
    np.testing.assert_allclose(l1, l2, rtol=1e-5)


@pytest.mark.slow
def test_gpt_sync_params_back():
    mesh_mod._global_mesh, mesh_mod._hcg = None, None
    cfg = gpt_tiny_config()
    model = GPTForPretraining(GPTModel(cfg))
    hcg = HybridCommunicateGroup(dp_degree=1, mp_degree=2, pp_degree=2)
    step = GPTHybridTrainStep(model, cfg, hcg, n_micro=2)
    ids, labels = _batch(cfg, 4, 16, seed=4)
    step(ids, labels)
    w_before = model.gpt.layers[0].wqkv.numpy().copy()
    step.sync_params_to_model()
    w_after = model.gpt.layers[0].wqkv.numpy()
    assert not np.array_equal(w_before, w_after)
    np.testing.assert_array_equal(
        w_after, np.asarray(step.params["blocks"]["wqkv"][0]))


def test_chunked_vocab_ce_matches_full():
    """The remat-chunked CE path (large vocab) must equal the full-logits
    path in value and gradient."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.models.gpt import vocab_parallel_cross_entropy

    rng = np.random.default_rng(0)
    # N=2304 > _CE_CHUNK and V >= 16384 -> chunked (2 chunks), with a
    # non-zero pad tail (2304 % 2048) so the mask-0 padding path is covered
    B, S, H, V = 2, 1152, 32, 16384
    h = jnp.asarray(rng.standard_normal((B, S, H)), jnp.float32)
    w = jnp.asarray(rng.standard_normal((V, H)) * 0.02, jnp.float32)
    lab = jnp.asarray(rng.integers(0, V, (B, S)), jnp.int32)

    def full(hh, ww):
        lg = jnp.einsum("bsh,vh->bsv", hh, ww).astype(jnp.float32)
        m = jax.lax.stop_gradient(jnp.max(lg, -1))
        lse = jnp.log(jnp.sum(jnp.exp(lg - m[..., None]), -1)) + m
        tgt = jnp.take_along_axis(lg, lab[..., None], -1)[..., 0]
        return jnp.mean(lse - tgt)

    got = float(vocab_parallel_cross_entropy(h, w, lab))
    want = float(full(h, w))
    assert abs(got - want) < 1e-4
    g1 = jax.grad(lambda a, b: vocab_parallel_cross_entropy(a, b, lab))(h, w)
    g2 = jax.grad(full)(h, w)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2),
                               rtol=2e-4, atol=1e-6)


@pytest.mark.slow
def test_generator_matches_full_forward_greedy():
    """KV-cache incremental decode == repeated full-forward argmax."""
    import jax.numpy as jnp
    from paddle_tpu.models.gpt import (
        GPTForPretraining, GPTModel, GPTGenerator, gpt_tiny_config,
        gpt_block, _ln, _BLOCK_KEYS,
    )
    paddle.seed(0)
    cfg = gpt_tiny_config()
    model = GPTForPretraining(GPTModel(cfg))
    gen = GPTGenerator(model, temperature=0.0)

    rng = np.random.default_rng(0)
    prompt = rng.integers(0, cfg.vocab_size, (2, 8)).astype(np.int32)
    out = np.asarray(gen(prompt, max_new_tokens=6)._value)
    assert out.shape == (2, 14)
    np.testing.assert_array_equal(out[:, :8], prompt)

    # oracle: full forward + argmax, token by token
    gpt = model.gpt
    blocks = {k: jnp.stack([getattr(l, k)._value for l in gpt.layers])
              for k in _BLOCK_KEYS}
    wte = gpt.embeddings.word_embeddings._value
    wpe = gpt.embeddings.position_embeddings._value
    eps = cfg.layer_norm_epsilon

    def full_next(ids):
        h = wte[ids] + wpe[jnp.arange(ids.shape[1])]
        import jax
        h, _ = jax.lax.scan(lambda x, p: (gpt_block(p, x, eps), None),
                            h, blocks)
        h = _ln(h, gpt.lnf_w._value, gpt.lnf_b._value, eps)
        logits = jnp.einsum("bsh,vh->bsv", h, wte)
        return np.asarray(jnp.argmax(logits[:, -1], -1))

    ids = prompt.copy()
    for t in range(6):
        nxt = full_next(jnp.asarray(ids))
        np.testing.assert_array_equal(out[:, 8 + t], nxt,
                                      err_msg=f"token {t}")
        ids = np.concatenate([ids, nxt[:, None].astype(np.int32)], 1)


def test_generator_sampling_modes():
    from paddle_tpu.models.gpt import (GPTForPretraining, GPTModel,
                                       GPTGenerator, gpt_tiny_config)
    paddle.seed(1)
    cfg = gpt_tiny_config()
    model = GPTForPretraining(GPTModel(cfg))
    prompt = np.zeros((1, 4), np.int32)
    g1 = GPTGenerator(model, temperature=1.0, top_k=8, seed=7)
    g2 = GPTGenerator(model, temperature=1.0, top_k=8, seed=7)
    o1 = np.asarray(g1(prompt, max_new_tokens=8)._value)
    o2 = np.asarray(g2(prompt, max_new_tokens=8)._value)
    np.testing.assert_array_equal(o1, o2)  # same seed, same sample
    g3 = GPTGenerator(model, temperature=1.0, top_k=8, seed=8)
    o3 = np.asarray(g3(prompt, max_new_tokens=8)._value)
    assert o3.shape == o1.shape  # different seed may differ; just runs


@pytest.mark.slow
def test_bert_fused_mlm_loss_matches_criterion():
    """forward_with_mlm_loss == BertPretrainingCriterion(model(ids)) on
    both CE paths (full logits AND the chunked gate at V>=16384)."""
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.models.bert import (BertConfig, BertForPretraining,
                                        BertModel,
                                        BertPretrainingCriterion)

    for vocab, B, S in ((128, 2, 16), (16384, 5, 512)):
        cfg = BertConfig(vocab_size=vocab, hidden_size=32,
                         num_hidden_layers=1, num_attention_heads=2,
                         intermediate_size=64, max_position_embeddings=512,
                         hidden_dropout_prob=0.0,
                         attention_probs_dropout_prob=0.0)
        model = BertForPretraining(BertModel(cfg))
        model.eval()
        rng = np.random.default_rng(0)
        ids = paddle.to_tensor(
            rng.integers(0, vocab, (B, S)).astype(np.int64))
        labels_np = rng.integers(0, vocab, (B, S)).astype(np.int64)
        labels_np[0, :3] = -100  # ignore_index positions
        labels = paddle.to_tensor(labels_np)
        logits, nsp = model(ids)
        want = BertPretrainingCriterion(vocab)(logits, nsp, labels)
        got = model.forward_with_mlm_loss(ids, labels)
        np.testing.assert_allclose(float(got.numpy()),
                                   float(want.numpy()), rtol=2e-4)


def test_gpt_1f1b_matches_gpipe_oracle():
    """1F1B hybrid step (pp2 x mp2 x dp2, manual in-schedule backward)
    tracks the GPipe step exactly: same per-step losses over 4 steps means
    identical gradients through the optimizer (pipeline_parallel.py:119)."""
    mesh_mod._global_mesh, mesh_mod._hcg = None, None
    cfg = gpt_tiny_config()
    model = GPTForPretraining(GPTModel(cfg))
    hcg = HybridCommunicateGroup(dp_degree=2, mp_degree=2, pp_degree=2)
    oracle = GPTHybridTrainStep(model, cfg, hcg, n_micro=4, lr=1e-3,
                                remat=False)

    mesh_mod._global_mesh, mesh_mod._hcg = None, None
    model2 = GPTForPretraining(GPTModel(cfg))
    # same init
    for l1, l2 in zip(model.gpt.layers, model2.gpt.layers):
        for k in ("ln1_w", "ln1_b", "wqkv", "bqkv", "wo", "bo",
                  "ln2_w", "ln2_b", "w1", "b1", "w2", "b2"):
            getattr(l2, k).set_value(np.asarray(getattr(l1, k)._value))
    g1, g2 = model.gpt, model2.gpt
    g2.embeddings.word_embeddings.set_value(
        np.asarray(g1.embeddings.word_embeddings._value))
    g2.embeddings.position_embeddings.set_value(
        np.asarray(g1.embeddings.position_embeddings._value))
    g2.lnf_w.set_value(np.asarray(g1.lnf_w._value))
    g2.lnf_b.set_value(np.asarray(g1.lnf_b._value))
    hcg2 = HybridCommunicateGroup(dp_degree=2, mp_degree=2, pp_degree=2)
    step = GPTHybridTrainStep(model2, cfg, hcg2, n_micro=4, lr=1e-3,
                              remat=False, pipeline_schedule="1f1b")

    ids, labels = _batch(cfg, 8, 16, seed=11)
    for i in range(4):
        ref = float(oracle(ids, labels).numpy())
        got = float(step(ids, labels).numpy())
        np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-4,
                                   err_msg=f"step {i}")


@pytest.mark.slow
def test_gpt_hybrid_step_live_lr_schedule():
    """lr accepts an LRScheduler: each compiled step consumes the live
    value (traced input, no recompile) and advances the schedule."""
    from paddle_tpu.optimizer import lr as lr_mod
    mesh_mod._global_mesh, mesh_mod._hcg = None, None
    cfg = gpt_tiny_config()
    model = GPTForPretraining(GPTModel(cfg))
    hcg = HybridCommunicateGroup(dp_degree=2, mp_degree=2, pp_degree=2)
    sched = lr_mod.StepDecay(learning_rate=1e-2, step_size=2, gamma=0.1)
    step = GPTHybridTrainStep(model, cfg, hcg, n_micro=2, lr=sched)
    ids, labels = _batch(cfg, 4, 16, seed=3)
    step(ids, labels)
    step(ids, labels)
    assert abs(sched() - 1e-3) < 1e-9  # decayed after 2 steps
    step(ids, labels)
    assert step._compiled is not None  # no rebuild across lr changes


def test_gpt_bf16_master_and_moments_train():
    """param_dtype/moment_dtype bfloat16 (the storage mode that fits
    GPT-1.3B + Adam on one 16GB chip): state is stored bf16, update math
    stays f32, training still converges."""
    mesh_mod._global_mesh, mesh_mod._hcg = None, None
    cfg = gpt_tiny_config()
    model = GPTForPretraining(GPTModel(cfg))
    hcg = HybridCommunicateGroup(dp_degree=1, mp_degree=1, pp_degree=1)
    step = GPTHybridTrainStep(model, cfg, hcg, n_micro=1, lr=3e-3,
                              param_dtype="bfloat16",
                              moment_dtype="bfloat16")
    assert step.params["wte"].dtype == jnp.bfloat16
    assert step.opt_state["m"]["wte"].dtype == jnp.bfloat16
    ids, labels = _batch(cfg, 4, 16, seed=9)
    losses = [float(step(ids, labels).numpy()) for _ in range(8)]
    assert losses[-1] < losses[0], losses


def test_gpt_interleaved_1f1b_matches_oracle():
    """Interleaved 1F1B (pp=2 x vpp=2) tracks the pp=1 oracle step-for-step
    (pipeline_parallel.py:463 parity) — loss and grads through the
    optimizer over 3 steps."""
    cfg = gpt_tiny_config()  # 4 layers -> 2 stages x 2 chunks x 1 layer
    rng = np.random.default_rng(17)
    ids = rng.integers(0, cfg.vocab_size, size=(8, 16)).astype(np.int32)
    labels = np.roll(ids, -1, axis=1).astype(np.int32)

    losses = {}
    for pp, vpp, sched in ((1, 1, "gpipe"), (2, 2, "1f1b")):
        mesh_mod._global_mesh, mesh_mod._hcg = None, None
        paddle.seed(777)
        hcg = HybridCommunicateGroup(dp_degree=1, mp_degree=1,
                                     pp_degree=pp)
        model = GPTForPretraining(GPTModel(cfg))
        step = GPTHybridTrainStep(model, cfg, hcg, n_micro=4, lr=1e-3,
                                  virtual_pp_degree=vpp, remat=False,
                                  pipeline_schedule=sched)
        losses[(pp, vpp)] = [float(step(ids, labels).numpy())
                             for _ in range(3)]
    np.testing.assert_allclose(losses[(2, 2)], losses[(1, 1)],
                               rtol=2e-4, atol=2e-4)


def test_gpt_interleaved_1f1b_vpp3_odd_micro():
    """Edge stress: pp=2 x vpp=3 with n_micro=3 (not a multiple of pp)."""
    cfg = gpt_tiny_config(num_layers=6)
    rng = np.random.default_rng(18)
    ids = rng.integers(0, cfg.vocab_size, size=(6, 16)).astype(np.int32)
    labels = np.roll(ids, -1, axis=1).astype(np.int32)

    losses = {}
    for pp, vpp, sched in ((1, 1, "gpipe"), (2, 3, "1f1b")):
        mesh_mod._global_mesh, mesh_mod._hcg = None, None
        paddle.seed(55)
        hcg = HybridCommunicateGroup(dp_degree=1, mp_degree=1,
                                     pp_degree=pp)
        model = GPTForPretraining(GPTModel(cfg))
        step = GPTHybridTrainStep(model, cfg, hcg, n_micro=3, lr=1e-3,
                                  virtual_pp_degree=vpp, remat=False,
                                  pipeline_schedule=sched)
        losses[(pp, vpp)] = [float(step(ids, labels).numpy())
                             for _ in range(2)]
    np.testing.assert_allclose(losses[(2, 3)], losses[(1, 1)],
                               rtol=2e-4, atol=2e-4)


def test_gpt_1f1b_remat_matches_oracle():
    """VERDICT r4 #3: remat composed with pipeline_schedule="1f1b"
    (per-block checkpoint inside the per-tick vjp) must not change
    numerics — pp=2 x 1F1B with full and dots remat both track the pp=1
    no-remat oracle step-for-step."""
    cfg = gpt_tiny_config()
    rng = np.random.default_rng(23)
    ids = rng.integers(0, cfg.vocab_size, size=(8, 16)).astype(np.int32)
    labels = np.roll(ids, -1, axis=1).astype(np.int32)

    losses = {}
    for key, pp, sched, remat in (("oracle", 1, "gpipe", False),
                                  ("full", 2, "1f1b", True),
                                  ("dots", 2, "1f1b", "dots")):
        mesh_mod._global_mesh, mesh_mod._hcg = None, None
        paddle.seed(99)
        hcg = HybridCommunicateGroup(dp_degree=1, mp_degree=1,
                                     pp_degree=pp)
        model = GPTForPretraining(GPTModel(cfg))
        step = GPTHybridTrainStep(model, cfg, hcg, n_micro=4, lr=1e-3,
                                  remat=remat, pipeline_schedule=sched)
        losses[key] = [float(step(ids, labels).numpy()) for _ in range(3)]
    np.testing.assert_allclose(losses["full"], losses["oracle"],
                               rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(losses["dots"], losses["oracle"],
                               rtol=2e-4, atol=2e-4)


@pytest.mark.slow
def test_generator_flash_prefill_matches_xla():
    """Flash-kernel prefill (interpret mode here) produces the same KV
    caches/logits as the XLA prefill: greedy decodes agree exactly."""
    from paddle_tpu.models.gpt import GPTGenerator
    cfg = gpt_tiny_config(max_position_embeddings=256, hidden_size=128,
                          num_heads=2)
    model = GPTForPretraining(GPTModel(cfg))
    rng = np.random.default_rng(21)
    ids = rng.integers(0, cfg.vocab_size, (2, 128)).astype(np.int32)
    out_x = GPTGenerator(model, use_flash=False)(
        paddle.to_tensor(ids), max_new_tokens=6).numpy()
    out_f = GPTGenerator(model, use_flash=True)(
        paddle.to_tensor(ids), max_new_tokens=6).numpy()
    # the two attention implementations agree to float tolerance, not
    # bit-exactly; a near-tied argmax may flip a rare token, after which
    # the sequences legitimately diverge — demand near-total agreement
    agreement = (out_x == out_f).mean()
    assert agreement >= 0.95, (agreement, out_x, out_f)
