"""hapi Model/fit + paddle.metric tests.

Parity model: reference hapi tests (python/paddle/tests/test_model.py) fit a
small net on synthetic data and assert accuracy improves and checkpoints
round-trip; metric tests check streaming values against sklearn-style oracles
computed with numpy.
"""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer as opt
from paddle_tpu.io import Dataset
from paddle_tpu.metric import Accuracy, Precision, Recall, Auc, accuracy
from paddle_tpu.hapi import EarlyStopping


class SynthCls(Dataset):
    """Linearly separable 2-class blobs."""

    def __init__(self, n=256, d=8, seed=0):
        rng = np.random.default_rng(seed)
        self.x = rng.standard_normal((n, d)).astype(np.float32)
        w = rng.standard_normal((d,))
        self.y = (self.x @ w > 0).astype(np.int64)

    def __getitem__(self, i):
        return self.x[i], self.y[i]

    def __len__(self):
        return len(self.x)


def _mlp(d=8, classes=2):
    return nn.Sequential(nn.Linear(d, 32), nn.ReLU(), nn.Linear(32, classes))


def _model(net=None):
    net = net or _mlp()
    m = paddle.Model(net)
    m.prepare(optimizer=opt.Adam(learning_rate=1e-2,
                                 parameters=net.parameters()),
              loss=nn.CrossEntropyLoss(),
              metrics=Accuracy())
    return m


def test_fit_improves_and_evaluate(tmp_path):
    paddle.seed(0)
    m = _model()
    before = m.evaluate(SynthCls(), batch_size=64, verbose=0)
    m.fit(SynthCls(), batch_size=64, epochs=6, verbose=0)
    after = m.evaluate(SynthCls(), batch_size=64, verbose=0)
    assert after["acc"] > max(0.9, before["acc"])
    assert after["loss"][0] < before["loss"][0]


def test_predict_and_batch_apis():
    paddle.seed(1)
    m = _model()
    ds = SynthCls(n=32)
    outs = m.predict(ds, batch_size=16, stack_outputs=True, verbose=0)
    assert len(outs) == 1 and outs[0].shape == (32, 2)
    lv = m.train_batch([ds.x[:8]], [ds.y[:8]])
    loss_list = lv[0] if isinstance(lv, tuple) else lv
    assert np.isfinite(loss_list[0])
    ev = m.eval_batch([ds.x[:8]], [ds.y[:8]])
    ev_list = ev[0] if isinstance(ev, tuple) else ev
    assert np.isfinite(ev_list[0])


def test_save_load_roundtrip(tmp_path):
    paddle.seed(2)
    m = _model()
    m.fit(SynthCls(n=64), batch_size=32, epochs=1, verbose=0)
    path = str(tmp_path / "ckpt" / "model")
    m.save(path)
    assert os.path.exists(path + ".pdparams")
    assert os.path.exists(path + ".pdopt")

    net2 = _mlp()
    m2 = _model(net2)
    m2.load(path)
    x = SynthCls(n=4).x
    np.testing.assert_allclose(
        m.predict_batch([x])[0], m2.predict_batch([x])[0], rtol=1e-6)


def test_fit_with_checkpoint_callback(tmp_path):
    paddle.seed(3)
    m = _model()
    save_dir = str(tmp_path / "ckpts")
    m.fit(SynthCls(n=64), batch_size=32, epochs=2, verbose=0,
          save_dir=save_dir)
    assert os.path.exists(os.path.join(save_dir, "final.pdparams"))
    assert os.path.exists(os.path.join(save_dir, "0.pdparams"))


def test_early_stopping():
    paddle.seed(4)
    m = _model()
    # acc saturates at 1.0 on the separable set, triggering the stop
    es = EarlyStopping(monitor="acc", patience=1, verbose=0, mode="max")
    m.fit(SynthCls(n=32), eval_data=SynthCls(n=32), batch_size=32,
          epochs=50, verbose=0, callbacks=[es])
    assert m.stop_training  # stopped before the 50th epoch


def test_summary_counts_params():
    net = _mlp(8, 2)
    info = paddle.summary(net)
    # 8*32+32 + 32*2+2 = 354
    assert info["total_params"] == 354


def test_metric_accuracy_topk():
    m = Accuracy(topk=(1, 2))
    pred = np.array([[0.1, 0.7, 0.2], [0.5, 0.3, 0.2]], np.float32)
    label = np.array([[1], [2]])
    correct = m.compute(paddle.to_tensor(pred), paddle.to_tensor(label))
    m.update(correct)
    top1, top2 = m.accumulate()
    assert top1 == 0.5  # row0 right, row1 wrong
    assert top2 == 0.5  # row1's label 2 is 3rd even in top-2
    assert m.name() == ["acc_top1", "acc_top2"]


def test_metric_precision_recall():
    p, r = Precision(), Recall()
    preds = np.array([0.9, 0.8, 0.2, 0.6])
    labels = np.array([1, 0, 1, 1])
    p.update(preds, labels)
    r.update(preds, labels)
    # thresh 0.5: predicted pos = [1,1,0,1]; tp=2 fp=1 fn=1
    assert abs(p.accumulate() - 2 / 3) < 1e-9
    assert abs(r.accumulate() - 2 / 3) < 1e-9


def test_metric_auc_matches_exact():
    rng = np.random.default_rng(0)
    scores = rng.random(500)
    labels = (rng.random(500) < scores).astype(np.int64)  # correlated
    auc = Auc()
    auc.update(scores, labels)
    got = auc.accumulate()
    # exact AUC via rank statistic
    order = np.argsort(scores)
    ranks = np.empty(len(scores))
    ranks[order] = np.arange(1, len(scores) + 1)
    n_pos, n_neg = labels.sum(), (1 - labels).sum()
    exact = (ranks[labels == 1].sum() - n_pos * (n_pos + 1) / 2) / \
        (n_pos * n_neg)
    assert abs(got - exact) < 5e-3


def test_functional_accuracy():
    pred = np.array([[0.1, 0.9], [0.8, 0.2]], np.float32)
    label = np.array([[1], [1]])
    acc = accuracy(paddle.to_tensor(pred), paddle.to_tensor(label), k=1)
    assert float(np.asarray(acc._value)) == 0.5


def test_hapi_fit_multi_device_parallel():
    """Model.fit on the 8-device mesh: the compiled dp-sharded train step
    (reference distributed fit via prepare_distributed / data parallel)."""
    from paddle_tpu.distributed.mesh import HybridCommunicateGroup
    from paddle_tpu.distributed import mesh as mesh_mod
    saved = (mesh_mod._global_mesh, mesh_mod._hcg)
    try:
        mesh_mod._global_mesh, mesh_mod._hcg = None, None
        HybridCommunicateGroup(dp_degree=8)
        rng = np.random.default_rng(0)
        x = rng.standard_normal((64, 8)).astype(np.float32)
        w_true = rng.standard_normal((8, 1)).astype(np.float32)
        y = (x @ w_true).astype(np.float32)

        class DS(paddle.io.Dataset):
            def __len__(self):
                return 64
            def __getitem__(self, i):
                return x[i], y[i]

        net = nn.Linear(8, 1)
        model = paddle.Model(net)
        model.prepare(opt.SGD(learning_rate=0.1,
                              parameters=net.parameters()),
                      nn.MSELoss())
        assert model._use_parallel()  # mesh present, no metrics
        hist = model.fit(DS(), epochs=4, batch_size=16, verbose=0)
        assert model._parallel_step is not None  # compiled path engaged
        # loss went down and the EAGER network tracks the trained params
        out = net(paddle.to_tensor(x[:4]))
        np.testing.assert_allclose(out.numpy(), y[:4], atol=0.5)
    finally:
        mesh_mod._global_mesh, mesh_mod._hcg = saved


def test_hapi_fit_static_adapter():
    """Model.fit under enable_static: forward+loss+minimize captured into
    ONE Program and run through the Executor (the reference's
    _StaticGraphAdapter role)."""
    from paddle_tpu import static
    rng = np.random.default_rng(1)
    x = rng.standard_normal((32, 4)).astype(np.float32)
    y = (x @ rng.standard_normal((4, 1))).astype(np.float32)

    class DS(paddle.io.Dataset):
        def __len__(self):
            return 32
        def __getitem__(self, i):
            return x[i], y[i]

    net = nn.Linear(4, 1)
    model = paddle.Model(net)
    model.prepare(opt.SGD(learning_rate=0.1, parameters=net.parameters()),
                  nn.MSELoss())
    static.enable_static()
    try:
        l0 = model.train_batch([x[:8]], [y[:8]])[0]
        for _ in range(30):
            l1 = model.train_batch([x[:8]], [y[:8]])[0]
        assert l1 < l0, (l0, l1)
        assert model._static_state is not None
    finally:
        static.disable_static()
