"""HeterPs (HBM-cached embedding over host PS tables) and
HybridParallelInferenceHelper (micro-batched mesh inference)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import static
from paddle_tpu.distributed.ps import HeterPs, PsLocalClient, SGDAccessor


def _client(dim=4):
    c = PsLocalClient()
    c.create_sparse_table(0, emb_dim=dim, accessor=SGDAccessor(),
                          initializer=lambda: np.zeros(dim, np.float32))
    return c


def test_heter_ps_pull_matches_host():
    c = _client()
    hot = HeterPs(c, table_id=0, emb_dim=4, cache_slots=8)
    ids = np.array([1, 2, 3, 1], np.int64)
    out = np.asarray(hot.pull(ids))
    ref = np.asarray(c.pull_sparse(0, ids))
    np.testing.assert_allclose(out, ref)
    assert out.shape == (4, 4)
    # second pull is all hits
    h0 = hot.hits
    hot.pull(ids)
    assert hot.hits == h0 + 4 and hot.misses == 3


def test_heter_ps_push_keeps_cache_and_host_consistent():
    c = _client()
    hot = HeterPs(c, table_id=0, emb_dim=4, cache_slots=8)
    ids = np.array([10, 11], np.int64)
    hot.pull(ids)
    hot.push(ids, np.ones((2, 4), np.float32))
    # host applied sgd lr=0.01; cached copy must match host truth
    np.testing.assert_allclose(np.asarray(hot.pull(ids)), -0.01,
                               rtol=1e-5)
    np.testing.assert_allclose(np.asarray(c.pull_sparse(0, ids)), -0.01,
                               rtol=1e-5)


def test_heter_ps_eviction_is_lossless():
    """Cache far smaller than vocabulary: rows evict and reload from the
    host with no value drift (host is source of truth)."""
    c = _client()
    hot = HeterPs(c, table_id=0, emb_dim=4, cache_slots=4)
    for wave in range(3):
        ids = np.arange(wave * 4, wave * 4 + 4, dtype=np.int64)
        hot.pull(ids)
        hot.push(ids, np.full((4, 4), 1.0, np.float32))
    # every previously-touched id reloads with its trained value; a batch
    # bigger than the cache serves straight from the host, still correct
    all_ids = np.arange(12, dtype=np.int64)
    np.testing.assert_allclose(np.asarray(hot.pull(all_ids)), -0.01,
                               rtol=1e-5)
    assert len(hot._slot_of) <= 4
    fresh = np.asarray(hot.pull(np.arange(100, 104, dtype=np.int64)))
    np.testing.assert_allclose(fresh, 0.0)


def test_heter_ps_2d_batch_shape():
    c = _client()
    hot = HeterPs(c, table_id=0, emb_dim=4, cache_slots=16)
    out = hot.pull(np.arange(6, dtype=np.int64).reshape(2, 3))
    assert np.asarray(out).shape == (2, 3, 4)
    hot.end_pass()
    assert hot._slot_of == {}


def test_hybrid_parallel_inference_microbatches_match_direct():
    from paddle_tpu.distributed.fleet.utils import (
        HybridParallelInferenceHelper)

    static.enable_static()
    try:
        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            x = static.data("x", [2, 6], "float32")
            lin = paddle.nn.Linear(6, 3)
            out = paddle.tanh(lin(x))
        exe = static.Executor()
        exe.run(startup)

        helper = HybridParallelInferenceHelper(
            startup, main, num_mp=1, num_pp=1, micro_batch_size=2,
            init_comm=False)
        helper.gen_infer_program()

        rng = np.random.default_rng(0)
        big = rng.standard_normal((8, 6)).astype(np.float32)
        (got,) = helper.run(exe, {"x": big}, fetch_list=[out])
        # oracle: direct micro-batched runs
        want = np.concatenate([
            exe.run(main, feed={"x": big[i:i + 2]}, fetch_list=[out])[0]
            for i in range(0, 8, 2)])
        np.testing.assert_allclose(got, want, rtol=1e-6)
        assert got.shape == (8, 3)
    finally:
        static.disable_static()
