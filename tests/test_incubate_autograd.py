"""paddle.incubate.autograd functional transforms vs analytic oracles."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.incubate.autograd import Hessian, Jacobian, jvp, vjp


def test_vjp_matches_reference_example():
    def func(x):
        return paddle.matmul(x, x)

    x = paddle.ones([2, 2], dtype="float32")
    out, g = vjp(func, x)
    np.testing.assert_allclose(np.asarray(out.numpy()), 2 * np.ones((2, 2)))
    np.testing.assert_allclose(np.asarray(g.numpy()), 4 * np.ones((2, 2)))

    v = paddle.to_tensor(np.array([[1.0, 0.0], [0.0, 0.0]], np.float32))
    _, g2 = vjp(func, x, v)
    np.testing.assert_allclose(np.asarray(g2.numpy()),
                               [[2.0, 1.0], [1.0, 0.0]])


def test_jvp_scalar_and_multi_input():
    def func(x):
        return paddle.sum(paddle.square(x))

    x = paddle.to_tensor(np.arange(3, dtype=np.float32))
    out, dot = jvp(func, x)  # v = ones -> sum(2x)
    assert float(out.numpy()) == 5.0
    assert float(dot.numpy()) == pytest.approx(2 * (0 + 1 + 2))

    def f2(a, b):
        return paddle.sum(a * b)

    a = paddle.to_tensor(np.array([1.0, 2.0], np.float32))
    b = paddle.to_tensor(np.array([3.0, 4.0], np.float32))
    va = paddle.to_tensor(np.array([1.0, 0.0], np.float32))
    vb = paddle.to_tensor(np.array([0.0, 1.0], np.float32))
    _, dot2 = jvp(f2, [a, b], [va, vb])
    # d(sum(ab)) = b.va + a.vb = 3 + 2
    assert float(dot2.numpy()) == pytest.approx(5.0)


def test_jacobian_full_and_batched():
    A = np.array([[1.0, 2.0], [3.0, 4.0]], np.float32)

    def lin(x):
        return paddle.matmul(x, paddle.to_tensor(A))

    x = paddle.ones([1, 2], dtype="float32")
    J = Jacobian(lin, x)
    assert J.shape == (2, 2)
    np.testing.assert_allclose(J[:].numpy(), A.T)

    xb = paddle.ones([3, 2], dtype="float32")
    Jb = Jacobian(lin, xb, is_batched=True)
    assert Jb.shape == (3, 2, 2)
    for i in range(3):
        np.testing.assert_allclose(Jb[i].numpy(), A.T)


def test_hessian_quadratic():
    Q = np.array([[2.0, 1.0], [1.0, 4.0]], np.float32)

    def quad(x):
        return 0.5 * paddle.sum(x * paddle.matmul(x, paddle.to_tensor(Q)))

    x = paddle.to_tensor(np.array([1.0, -1.0], np.float32))
    H = Hessian(quad, x)
    assert H.shape == (2, 2)
    np.testing.assert_allclose(H[:].numpy(), Q, atol=1e-6)

    with pytest.raises(ValueError, match="scalar"):
        Hessian(lambda x: x, x)
