"""incubate/io/vision/jit/autograd round-3 tail parity."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu import incubate

rng = np.random.default_rng(0)


def _t(a):
    return paddle.to_tensor(np.asarray(a))


def test_lookahead_interpolates_slow_weights():
    lin = nn.Linear(2, 1)
    inner = paddle.optimizer.SGD(learning_rate=0.5,
                                 parameters=lin.parameters())
    opt = incubate.LookAhead(inner, alpha=0.5, k=2)
    w0 = lin.weight.numpy().copy()
    x = _t(np.ones((4, 2), np.float32))
    for step in range(2):
        loss = (lin(x) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
    # after k steps the weights were pulled halfway back toward w0's
    # trajectory: fast-only would differ
    w_look = lin.weight.numpy().copy()
    lin2 = nn.Linear(2, 1)
    lin2.weight.set_value(w0)
    lin2.bias.set_value(np.zeros_like(lin2.bias.numpy()))
    assert opt.state_dict()["lookahead_step"] == 2
    assert not np.allclose(w_look, w0)


def test_model_average_applies_mean():
    lin = nn.Linear(2, 1)
    ma = incubate.ModelAverage(parameters=lin.parameters())
    vals = []
    for v in (1.0, 3.0):
        lin.weight.set_value(np.full((2, 1), v, np.float32))
        ma.step()
        vals.append(v)
    with ma.apply():
        np.testing.assert_allclose(lin.weight.numpy(), np.mean(vals))
    np.testing.assert_allclose(lin.weight.numpy(), 3.0)  # restored


def test_segment_ops_and_identity_loss():
    data = _t(np.array([[1.0, 2.0], [3.0, 4.0], [5.0, 6.0]], np.float32))
    seg = _t(np.array([0, 0, 1]))
    np.testing.assert_allclose(
        incubate.segment_sum(data, seg).numpy(), [[4, 6], [5, 6]])
    np.testing.assert_allclose(
        incubate.segment_mean(data, seg).numpy(), [[2, 3], [5, 6]])
    out = incubate.identity_loss(data, reduction="sum")
    np.testing.assert_allclose(out.numpy(), 21.0)


def test_softmax_mask_fuse_variants():
    x = rng.standard_normal((2, 1, 4, 4)).astype(np.float32)
    mask = np.where(rng.random((2, 1, 4, 4)) > 0.5, 0.0, -1e30) \
        .astype(np.float32)
    got = incubate.softmax_mask_fuse(_t(x), _t(mask)).numpy()
    import scipy.special as sp
    want = sp.softmax(np.where(mask < -1e20, -np.inf, x + mask), axis=-1)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)

    got = incubate.softmax_mask_fuse_upper_triangle(_t(x)).numpy()
    tri = np.tril(np.ones((4, 4), bool))
    want = sp.softmax(np.where(tri, x, -np.inf), axis=-1)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def _toy_graph():
    """CSC: node v's in-neighbors are row[colptr[v]:colptr[v+1]]."""
    # 0 <- 1,2 ; 1 <- 2 ; 2 <- 0
    row = _t(np.array([1, 2, 2, 0], np.int64))
    colptr = _t(np.array([0, 2, 3, 4], np.int64))
    return row, colptr


def test_graph_sample_neighbors_full_and_capped():
    row, colptr = _toy_graph()
    nb, cnt = incubate.graph_sample_neighbors(
        row, colptr, _t(np.array([0, 2], np.int64)))
    np.testing.assert_array_equal(cnt.numpy(), [2, 1])
    np.testing.assert_array_equal(nb.numpy(), [1, 2, 0])
    nb, cnt = incubate.graph_sample_neighbors(
        row, colptr, _t(np.array([0], np.int64)), sample_size=1)
    assert cnt.numpy()[0] == 1 and nb.numpy()[0] in (1, 2)


def test_graph_reindex_compacts_ids():
    x = _t(np.array([10, 30], np.int64))
    neighbors = _t(np.array([30, 20, 10], np.int64))
    count = _t(np.array([2, 1], np.int64))
    src, dst, nodes = incubate.graph_reindex(x, neighbors, count)
    assert nodes.numpy()[0] == 10 and len(nodes.numpy()) == 3
    np.testing.assert_array_equal(dst.numpy(), [0, 0, 1])
    assert src.numpy()[2] == 0  # neighbor 10 reuses x's id slot


def test_graph_khop_sampler_shapes():
    row, colptr = _toy_graph()
    src, dst, sample_idx, reindex_x = incubate.graph_khop_sampler(
        row, colptr, _t(np.array([0], np.int64)), [2, 2])
    assert len(src.numpy()) == len(dst.numpy()) >= 2
    assert set(reindex_x.numpy()) <= set(range(len(sample_idx.numpy())))


def test_io_get_worker_info_in_worker():
    import paddle_tpu.io as io

    assert io.get_worker_info() is None  # main process

    class DS(io.Dataset):
        def __len__(self):
            return 4

        def __getitem__(self, i):
            wi = io.get_worker_info()
            assert wi is not None and wi.num_workers == 2
            return np.array([wi.id], np.int64)

    dl = io.DataLoader(DS(), batch_size=2, num_workers=2)
    ids = np.concatenate([np.asarray(b[0] if isinstance(b, (list, tuple))
                                     else b).reshape(-1) for b in dl])
    assert set(ids.tolist()) <= {0, 1}


def test_vision_image_backend(tmp_path):
    from paddle_tpu import vision

    assert vision.get_image_backend() == "pil"
    with pytest.raises(ValueError, match="pil/cv2/tensor"):
        vision.set_image_backend("nope")
    from PIL import Image
    p = str(tmp_path / "img.png")
    Image.fromarray(np.zeros((4, 5, 3), np.uint8)).save(p)
    img = vision.image_load(p)
    assert img.size == (5, 4)
    vision.set_image_backend("tensor")
    try:
        arr = vision.image_load(p)
        assert arr.shape == (4, 5, 3)
    finally:
        vision.set_image_backend("pil")


def test_program_translator_toggle():
    from paddle_tpu import jit

    calls = []

    @jit.to_static
    def f(x):
        calls.append(1)
        return x * 2

    pt = jit.ProgramTranslator()
    assert pt is jit.ProgramTranslator.get_instance()
    pt.enable(False)
    try:
        out = f(_t(np.array([3.0], np.float32)))
        np.testing.assert_allclose(out.numpy(), 6.0)
    finally:
        pt.enable(True)
    out = f(_t(np.array([4.0], np.float32)))
    np.testing.assert_allclose(out.numpy(), 8.0)
    jit.set_verbosity(1)
    jit.set_code_level(50)


def test_saved_tensors_hooks_pack_unpack():
    from paddle_tpu.autograd import PyLayer, saved_tensors_hooks

    packed, unpacked = [], []

    def pack(t):
        packed.append(1)
        return np.asarray(t.numpy())  # offload to host

    def unpack(a):
        unpacked.append(1)
        return paddle.to_tensor(a)

    class Square(PyLayer):
        @staticmethod
        def forward(ctx, x):
            ctx.save_for_backward(x)
            return x * x

        @staticmethod
        def backward(ctx, grad):
            (x,) = ctx.saved_tensor()
            return grad * 2 * x

    with saved_tensors_hooks(pack, unpack):
        x = _t(np.array([3.0], np.float32))
        x.stop_gradient = False
        y = Square.apply(x)
        y.backward()
    assert packed and unpacked
    np.testing.assert_allclose(x.grad.numpy(), 6.0)
