"""IO + vision tests: DataLoader pipeline and an end-to-end training slice."""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as opt
from paddle_tpu.io import (DataLoader, TensorDataset, BatchSampler,
                           DistributedBatchSampler, Dataset)
from paddle_tpu.vision.datasets import FakeData
from paddle_tpu.vision.models import resnet18, LeNet
from paddle_tpu.vision import transforms as T


def test_dataloader_batching():
    ds = TensorDataset([paddle.to_tensor(np.arange(30).reshape(10, 3).astype("float32")),
                        paddle.to_tensor(np.arange(10))])
    dl = DataLoader(ds, batch_size=4, drop_last=False)
    batches = list(dl)
    assert len(batches) == 3
    assert batches[0][0].shape == [4, 3]
    assert batches[2][0].shape == [2, 3]


def test_dataloader_threaded_prefetch():
    ds = FakeData(size=16, image_shape=(3, 8, 8), num_classes=4)
    dl = DataLoader(ds, batch_size=4, num_workers=2)
    n = 0
    for img, lab in dl:
        assert img.shape == [4, 3, 8, 8]
        n += 1
    assert n == 4


def test_distributed_batch_sampler_partitions():
    ds = FakeData(size=20, image_shape=(1,), num_classes=2)
    seen = []
    for rank in range(4):
        s = DistributedBatchSampler(ds, batch_size=5, num_replicas=4, rank=rank)
        for batch in s:
            seen.extend(batch)
    assert sorted(seen) == list(range(20))


def test_transforms_pipeline():
    img = (np.random.rand(32, 32, 3) * 255).astype(np.uint8)
    pipe = T.Compose([T.Resize(16), T.CenterCrop(8), T.ToTensor(),
                      T.Normalize([0.5, 0.5, 0.5], [0.5, 0.5, 0.5])])
    out = pipe(img)
    assert out.shape == (3, 8, 8)
    assert out.dtype == np.float32


def test_lenet_trains_on_fake_mnist():
    paddle.seed(7)
    model = LeNet()
    o = opt.Adam(1e-3, parameters=model.parameters())
    ce = nn.CrossEntropyLoss()
    # tiny memorization task: 8 fixed samples
    x = paddle.to_tensor(np.random.rand(8, 1, 28, 28).astype("float32"))
    y = paddle.to_tensor(np.arange(8) % 10)
    first = None
    for i in range(30):
        loss = ce(model(x), y)
        loss.backward()
        o.step()
        o.clear_grad()
        if first is None:
            first = float(loss)
    assert float(loss) < first * 0.7


def test_resnet18_forward_backward():
    model = resnet18(num_classes=10)
    x = paddle.to_tensor(np.random.rand(2, 3, 32, 32).astype("float32"))
    out = model(x)
    assert out.shape == [2, 10]
    out.sum().backward()
    grads = [p.grad for p in model.parameters() if p.grad is not None]
    assert len(grads) == len([p for p in model.parameters() if p.trainable])


def test_paddle_save_load_model(tmp_path):
    m = nn.Linear(3, 3)
    path = str(tmp_path / "model.pdparams")
    paddle.save(m.state_dict(), path)
    loaded = paddle.load(path)
    m2 = nn.Linear(3, 3)
    m2.set_state_dict(loaded)
    np.testing.assert_allclose(m.weight.numpy(), m2.weight.numpy())
