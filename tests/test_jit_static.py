"""to_static + static graph facade tests (parity model: dygraph_to_static tests —
dygraph output must equal compiled output)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as opt
from paddle_tpu.jit import to_static, save as jit_save, load as jit_load
from paddle_tpu.jit.save_load import InputSpec
import paddle_tpu.static as static


def test_to_static_matches_eager():
    paddle.seed(0)
    m = nn.Sequential(nn.Linear(4, 8), nn.GELU(), nn.Linear(8, 2))
    x = paddle.to_tensor(np.random.rand(3, 4).astype("float32"))
    eager_out = m(x).numpy()
    ms = to_static(m)
    static_out = ms(x).numpy()
    np.testing.assert_allclose(eager_out, static_out, rtol=1e-5)


def test_to_static_backward_flows():
    m = nn.Linear(4, 4)
    ms = to_static(m)
    x = paddle.to_tensor(np.random.rand(2, 4).astype("float32"))
    loss = ms(x).sum()
    loss.backward()
    assert m.weight.grad is not None
    np.testing.assert_allclose(m.weight.grad.numpy(),
                               np.outer(x.numpy().sum(0), np.ones(4)), rtol=1e-5)


def test_to_static_training_with_optimizer():
    paddle.seed(1)
    m = nn.Sequential(nn.Linear(2, 16), nn.Tanh(), nn.Linear(16, 1))
    ms = to_static(m)
    o = opt.Adam(0.02, parameters=m.parameters())
    x = paddle.to_tensor(np.random.rand(16, 2).astype("float32"))
    y = paddle.to_tensor((x.numpy() @ np.array([[2.0], [-1.0]], "float32")))
    losses = []
    for _ in range(40):
        loss = ((ms(x) - y) ** 2).mean()
        loss.backward()
        o.step()
        o.clear_grad()
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.3


def test_to_static_recompiles_per_shape():
    m = nn.Linear(4, 2)
    ms = to_static(m)
    a = ms(paddle.to_tensor(np.random.rand(2, 4).astype("float32")))
    b = ms(paddle.to_tensor(np.random.rand(5, 4).astype("float32")))
    assert a.shape == [2, 2] and b.shape == [5, 2]
    assert len(ms.forward.concrete_programs) == 2


def test_jit_save_load(tmp_path):
    m = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    m.eval()
    x = paddle.to_tensor(np.random.rand(3, 4).astype("float32"))
    ref = m(x).numpy()
    path = str(tmp_path / "model")
    jit_save(m, path, input_spec=[InputSpec([3, 4], "float32")])
    loaded = jit_load(path)
    out = loaded(x)
    np.testing.assert_allclose(out.numpy(), ref, rtol=1e-5)


def test_static_program_forward():
    static.enable_static()
    try:
        main = static.Program()
        startup = static.Program()
        with static.program_guard(main, startup):
            x = static.data("x", [4, 3], "float32")
            l = nn.Linear(3, 2)
            y = l(x)
        exe = static.Executor()
        x_np = np.random.rand(4, 3).astype("float32")
        (out,) = exe.run(main, feed={"x": x_np}, fetch_list=[y])
        ref = x_np @ l.weight.numpy() + l.bias.numpy()
        np.testing.assert_allclose(out, ref, rtol=1e-5)
    finally:
        static.disable_static()


def test_static_training_minimize():
    static.enable_static()
    try:
        main = static.Program()
        startup = static.Program()
        with static.program_guard(main, startup):
            x = static.data("x", [8, 2], "float32")
            yt = static.data("y", [8, 1], "float32")
            l = nn.Linear(2, 1)
            pred = l(x)
            loss = ((pred - yt) ** 2).mean()
            sgd = opt.SGD(0.1, parameters=l.parameters())
            sgd.minimize(loss)
        exe = static.Executor()
        exe.run(startup)
        x_np = np.random.rand(8, 2).astype("float32")
        y_np = x_np @ np.array([[1.0], [2.0]], "float32")
        losses = []
        for _ in range(50):
            (lv,) = exe.run(main, feed={"x": x_np, "y": y_np},
                            fetch_list=[loss])
            losses.append(float(lv))
        assert losses[-1] < losses[0] * 0.1
    finally:
        static.disable_static()


def test_static_inference_model_roundtrip(tmp_path):
    static.enable_static()
    try:
        main = static.Program()
        with static.program_guard(main):
            x = static.data("x", [2, 3], "float32")
            l = nn.Linear(3, 4)
            y = l(x)
        exe = static.Executor()
        x_np = np.random.rand(2, 3).astype("float32")
        (ref,) = exe.run(main, feed={"x": x_np}, fetch_list=[y])
        prefix = str(tmp_path / "infer")
        static.save_inference_model(prefix, [x], [y], exe, program=main)
        predict, feed_names, _ = static.load_inference_model(prefix, exe)
        (out,) = predict(x_np)
        np.testing.assert_allclose(out, ref, rtol=1e-5)
    finally:
        static.disable_static()


def test_traced_dropout_varies_across_calls():
    m = nn.Dropout(0.5)
    ms = to_static(lambda t: m(t))
    x = paddle.to_tensor(np.ones((64,), "float32"))
    a = ms(x).numpy()
    b = ms(x).numpy()
    assert not np.array_equal(a, b)  # per-call rng threading works under jit
