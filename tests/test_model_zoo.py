"""Vision model zoo + BERT + device tests.

Parity model: reference vision model tests (test_vision_models.py) run each
family forward at 1x3x224x224 and check output shape; BERT fixture follows
dygraph_to_static/bert_dygraph_model.py (pretraining loss trains down).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, ops, optimizer as opt
from paddle_tpu.vision import models as M


def _img(n=1, size=64):
    rng = np.random.default_rng(0)
    return paddle.to_tensor(
        rng.standard_normal((n, 3, size, size)).astype(np.float32))


@pytest.mark.parametrize("builder,size", [
    (lambda: M.densenet121(num_classes=10), 64),
    (lambda: M.squeezenet1_0(num_classes=10), 64),
    (lambda: M.squeezenet1_1(num_classes=10), 64),
    (lambda: M.mobilenet_v1(scale=0.25, num_classes=10), 64),
    (lambda: M.mobilenet_v3_small(num_classes=10), 64),
    (lambda: M.mobilenet_v3_large(num_classes=10), 64),
    (lambda: M.shufflenet_v2_x0_25(num_classes=10), 64),
    (lambda: M.inception_v3(num_classes=10), 128),
])
@pytest.mark.slow
def test_vision_model_forward(builder, size):
    paddle.seed(0)
    net = builder()
    net.eval()
    out = net(_img(1, size))
    assert tuple(out.shape) == (1, 10)
    assert np.isfinite(np.asarray(out._value)).all()


@pytest.mark.slow
def test_googlenet_returns_aux():
    paddle.seed(0)
    net = M.googlenet(num_classes=10)
    net.eval()
    out, aux1, aux2 = net(_img(1, 64))
    for o in (out, aux1, aux2):
        assert tuple(o.shape) == (1, 10)


@pytest.mark.slow
def test_densenet_trains():
    paddle.seed(1)
    net = M.densenet121(num_classes=2)
    o = opt.Adam(learning_rate=1e-3, parameters=net.parameters())
    x = _img(4, 64)
    y = paddle.to_tensor(np.array([0, 1, 0, 1], np.int64))
    lossfn = nn.CrossEntropyLoss()
    losses = []
    for _ in range(3):
        loss = lossfn(net(x), y)
        loss.backward()
        o.step()
        o.clear_grad()
        losses.append(float(np.asarray(loss._value)))
    assert losses[-1] < losses[0]


def test_bert_pretraining_trains():
    from paddle_tpu.models.bert import (
        BertModel, BertForPretraining, BertPretrainingCriterion,
        bert_tiny_config,
    )
    paddle.seed(2)
    cfg = bert_tiny_config()
    model = BertForPretraining(BertModel(cfg))
    crit = BertPretrainingCriterion(cfg.vocab_size)
    o = opt.AdamW(learning_rate=1e-3, parameters=model.parameters())

    rng = np.random.default_rng(3)
    B, S = 4, 32
    ids = rng.integers(0, cfg.vocab_size, (B, S)).astype(np.int64)
    mlm_labels = np.where(rng.random((B, S)) < 0.15, ids, -100)
    nsp = rng.integers(0, 2, (B,)).astype(np.int64)
    mask = np.ones((B, S), np.int64)
    mask[:, S - 4:] = 0  # padding tail

    losses = []
    for _ in range(8):
        scores, seq_rel = model(paddle.to_tensor(ids),
                                attention_mask=paddle.to_tensor(mask))
        loss = crit(scores, seq_rel, paddle.to_tensor(mlm_labels),
                    paddle.to_tensor(nsp))
        loss.backward()
        o.step()
        o.clear_grad()
        losses.append(float(np.asarray(loss._value)))
    assert losses[-1] < losses[0], losses
    # tied embeddings: decoder weight IS the word embedding table
    emb = model.bert.embeddings.word_embeddings.weight
    assert model.cls.decoder_weight is emb


def test_bert_compiled_matches_eager():
    from paddle_tpu.models.bert import BertModel, bert_tiny_config
    paddle.seed(4)
    bert = BertModel(bert_tiny_config())
    bert.eval()
    ids = np.random.default_rng(5).integers(0, 1024, (2, 16)).astype(np.int64)

    seq_eager, pooled_eager = bert(paddle.to_tensor(ids))

    @paddle.jit.to_static
    def f(x):
        return bert(x)

    seq_jit, pooled_jit = f(paddle.to_tensor(ids))
    np.testing.assert_allclose(np.asarray(seq_eager._value),
                               np.asarray(seq_jit._value), rtol=2e-5,
                               atol=2e-5)


def test_device_api():
    from paddle_tpu import device
    d = device.get_device()
    assert isinstance(d, str)
    assert device.device_count() >= 1
    p = device.set_device("cpu")
    assert repr(p) is not None
    assert device.get_device() == "cpu"
    assert not device.is_compiled_with_npu()
    assert device.cuda.device_count() == 0
