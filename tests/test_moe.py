"""MoE / expert-parallel tests.

Parity model: the reference validates MoELayer against dense mixtures
(/root/reference/python/paddle/fluid/tests/unittests/collective/
test_moe_api.py style); here the oracle is the explicit dense
sum_e(prob_e * expert_e(x)) at capacity -> infinity, plus drop semantics,
gradient flow, ep-sharded execution, and the grad-clip/moe_utils shims.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, ops
from paddle_tpu.framework.tensor import Tensor
from paddle_tpu.distributed import mesh as mesh_mod
from paddle_tpu.distributed.mesh import HybridCommunicateGroup
from paddle_tpu.incubate.distributed.models.moe import (
    MoELayer, ExpertLayer, NaiveGate, GShardGate, SwitchGate,
    ClipGradForMOEByGlobalNorm,
)
from paddle_tpu.distributed.utils import global_scatter, global_gather


@pytest.fixture(autouse=True)
def reset_mesh():
    saved = (mesh_mod._global_mesh, mesh_mod._hcg)
    yield
    mesh_mod._global_mesh, mesh_mod._hcg = saved


def _np(t):
    return np.asarray(t._value if hasattr(t, "_value") else t)


def _x(s=16, m=8, seed=0):
    rng = np.random.default_rng(seed)
    return paddle.to_tensor(rng.standard_normal((s, m)).astype(np.float32))


def test_single_expert_identity():
    """E=1, top-1 naive gate: MoE(x) == raw_gate_logit * expert(x)
    (the reference combines with the gate's raw top-k values — moe_layer.py:487
    bmm(value, x) with NaiveGate's unsoftmaxed logits)."""
    paddle.seed(0)
    expert = ExpertLayer(8, 16)
    moe = MoELayer(8, [expert], gate={"type": "naive", "top_k": 1},
                   capacity_factor=100.0)
    x = _x()
    got = _np(moe(x))
    logit = _np(moe.gate.gate(x))          # [S, 1]
    want = logit * _np(expert(x))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_dense_mixture_oracle():
    """top_k == E at huge capacity == the dense softmax mixture."""
    paddle.seed(1)
    E, M, S = 4, 8, 12
    experts = [ExpertLayer(M, 16) for _ in range(E)]
    moe = MoELayer(M, experts, gate={"type": "naive", "top_k": E},
                   capacity_factor=100.0)
    x = _x(S, M, seed=1)
    got = _np(moe(x))

    logits = _np(moe.gate.gate(x))
    # naive gate does not renormalize: combine weight = raw gate logit of the
    # top-k winners; with top_k == E every expert contributes its logit
    want = np.zeros((S, M), np.float32)
    for e in range(E):
        want += logits[:, e:e + 1] * _np(experts[e](x))
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_heterogeneous_experts_match_stacked():
    """The generic per-expert loop equals the stacked fast path."""
    paddle.seed(2)
    E, M = 2, 8

    class Slow(nn.Layer):  # same math as ExpertLayer but a different class
        def __init__(self, src):
            super().__init__()
            self.htoh4, self.h4toh, self.act = src.htoh4, src.h4toh, src.act

        def forward(self, x):
            return self.h4toh(nn.functional.gelu(self.htoh4(x)))

    experts = [ExpertLayer(M, 16) for _ in range(E)]
    fast = MoELayer(M, experts, gate={"type": "naive", "top_k": 1},
                    capacity_factor=100.0)
    slow = MoELayer(M, [Slow(e) for e in experts],
                    gate={"type": "naive", "top_k": 1}, capacity_factor=100.0)
    # identical gate weights
    slow.gate.gate.weight.set_value(_np(fast.gate.gate.weight))
    slow.gate.gate.bias.set_value(_np(fast.gate.gate.bias))
    x = _x(10, M, seed=3)
    assert fast._homogeneous_ffn() and not slow._homogeneous_ffn()
    np.testing.assert_allclose(_np(fast(x)), _np(slow(x)),
                               rtol=1e-5, atol=1e-5)


def test_capacity_drop_zeroes_tokens():
    """capacity 1 token/expert: overflow tokens produce zero output."""
    paddle.seed(3)
    E, M, S = 2, 4, 8
    moe = MoELayer(M, [ExpertLayer(M, 8) for _ in range(E)],
                   gate={"type": "naive", "top_k": 1},
                   capacity_factor=float(E) / S)  # C == 1
    x = _x(S, M, seed=4)
    out = _np(moe(x))
    # at most E tokens survive; the rest are exactly zero rows
    zero_rows = int((np.abs(out).sum(axis=1) == 0).sum())
    assert zero_rows >= S - E * 1


def test_gshard_switch_gates_and_backward():
    paddle.seed(4)
    M, S = 8, 16
    for gtype, topk in (("gshard", 2), ("switch", 1)):
        moe = MoELayer(M, [ExpertLayer(M, 16) for _ in range(4)],
                       gate={"type": gtype, "top_k": topk})
        x = _x(S, M, seed=5)
        x.stop_gradient = False
        out = moe(x)
        aux = moe.gate.get_loss()
        assert aux is not None and np.isfinite(float(_np(aux)))
        loss = ops.mean(out * out) + aux
        loss.backward()
        g = moe.gate.gate.weight.grad
        assert g is not None and np.isfinite(_np(g)).all()
        anyexp = moe.experts[0].htoh4.weight.grad
        assert anyexp is not None and np.isfinite(_np(anyexp)).all()
        assert x.grad is not None


def test_moe_on_ep_axis_matches_single():
    """Same layer under an 8-way sharding (ep) mesh == no-mesh numerics."""
    paddle.seed(5)
    M = 8
    moe = MoELayer(M, [ExpertLayer(M, 16) for _ in range(8)],
                   gate={"type": "naive", "top_k": 2}, capacity_factor=100.0)
    x = _x(16, M, seed=6)
    want = _np(moe(x))
    HybridCommunicateGroup(dp_degree=1, sharding_degree=8)
    assert moe._ep_axis() == "sharding"
    got = _np(moe(x))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_moe_in_compiled_step():
    """MoE trains under the jitted to_static step (static shapes hold)."""
    paddle.seed(6)
    M = 8
    model = MoELayer(M, [ExpertLayer(M, 16) for _ in range(4)],
                     gate={"type": "gshard"})
    x = _x(16, M, seed=7)
    assert np.isfinite(float(_np(ops.mean(model(x) ** 2))))  # train mode runs

    # fn-form to_static bakes the module's mode at trace time; trace in eval
    # (no gshard random routing) so the compiled program is deterministic
    model.eval()

    @paddle.jit.to_static
    def step(x):
        out = model(x)
        return ops.mean(out * out)

    v1 = float(_np(step(x)))
    v2 = float(_np(step(x)))
    assert np.isfinite(v1) and v1 == v2  # deterministic, compiled


def test_moe_grad_clip():
    paddle.seed(7)
    M = 8
    moe = MoELayer(M, [ExpertLayer(M, 16) for _ in range(2)],
                   gate={"type": "naive", "top_k": 1})
    x = _x(8, M)
    out = moe(x)
    ops.mean(out * out).backward()
    pg = [(p, p.grad) for p in moe.parameters() if p.grad is not None]
    clip = ClipGradForMOEByGlobalNorm(
        0.01, is_expert_param_func=lambda p: True)
    clipped = clip(pg)
    total = np.sqrt(sum(float((_np(g) ** 2).sum()) for _, g in clipped))
    assert total <= 0.0101


def test_global_scatter_gather_roundtrip():
    x = _x(6, 4)
    lc = paddle.to_tensor(np.array([2, 4], np.int64))
    y = global_scatter(x, lc, lc)
    z = global_gather(y, lc, lc)
    np.testing.assert_allclose(_np(z), _np(x))


@pytest.mark.slow
def test_ep_alltoall_dispatch_matches_dense_oracle():
    """Compiled-path MoE: ep-axis all_to_all dispatch (8-way CPU mesh,
    tokens + experts sharded over ep) == the dense single-device program,
    values AND gradients (global_scatter/global_gather parity)."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu._jax_compat import shard_map
    from jax.sharding import PartitionSpec as P
    from paddle_tpu.distributed.mesh import HybridCommunicateGroup
    from paddle_tpu.distributed import mesh as mesh_mod
    from paddle_tpu.incubate.distributed.models.moe import ep_moe_ffn

    mesh_mod._global_mesh, mesh_mod._hcg = None, None
    hcg = HybridCommunicateGroup(dp_degree=1, sharding_degree=8)
    mesh = hcg.mesh
    ep = 8
    E, S, M, H = 8, 64, 16, 32
    S_local = S // ep
    rng = np.random.default_rng(11)
    f32 = lambda *s: rng.standard_normal(s).astype(np.float32)
    x = jnp.asarray(f32(S, M))
    gw, gb = jnp.asarray(f32(M, E) * 0.5), jnp.asarray(f32(E) * 0.1)
    w1, b1 = jnp.asarray(f32(E, M, H) * 0.2), jnp.asarray(f32(E, H) * 0.1)
    w2, b2 = jnp.asarray(f32(E, H, M) * 0.2), jnp.asarray(f32(E, M) * 0.1)

    def sharded(x, gw, gb, w1, b1, w2, b2):
        def prog(xl, gw, gb, w1l, b1l, w2l, b2l):
            return ep_moe_ffn(xl, gw, gb, w1l, b1l, w2l, b2l,
                              ep_axis="sharding", num_expert=E,
                              capacity=S_local, top_k=2)
        return shard_map(
            prog, mesh=mesh,
            in_specs=(P("sharding"), P(), P(), P("sharding"), P("sharding"),
                      P("sharding"), P("sharding")),
            out_specs=P("sharding"), check_vma=False,
        )(x, gw, gb, w1, b1, w2, b2)

    def dense(x, gw, gb, w1, b1, w2, b2):
        return ep_moe_ffn(x, gw, gb, w1, b1, w2, b2, ep_axis=None,
                          num_expert=E, capacity=S, top_k=2)

    y_sh = jax.jit(sharded)(x, gw, gb, w1, b1, w2, b2)
    y_dn = dense(x, gw, gb, w1, b1, w2, b2)
    np.testing.assert_allclose(np.asarray(y_sh), np.asarray(y_dn),
                               rtol=1e-5, atol=1e-6)

    loss_sh = lambda *a: jnp.sum(jnp.square(sharded(*a)))
    loss_dn = lambda *a: jnp.sum(jnp.square(dense(*a)))
    gs = jax.grad(loss_sh, argnums=(0, 3, 5))(x, gw, gb, w1, b1, w2, b2)
    gd = jax.grad(loss_dn, argnums=(0, 3, 5))(x, gw, gb, w1, b1, w2, b2)
    for a, b, name in zip(gs, gd, ("x", "w1", "w2")):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-6,
                                   err_msg=f"d{name}")
