"""Fused Pallas MoE dispatch/combine kernels (kernels/moe_dispatch.py).

Tier-1 parity contract: the fused kernels == the gather-based reference
in CPU interpret mode — ragged token counts, capacity-overflow drops,
top-k 1 and 2, uneven expert load — plus gradients (reference-recompute
VJP), MoELayer(fused_dispatch=True) equivalence, trajectory equivalence
over a short train run, the PTCS004 fusion-opportunity diagnostic
(fires on the unfused chain, clean on the fused kernels), the fused
pallas_call cost-model pricing, and the moe_utils count diagnostics.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import ops
from paddle_tpu.distributed import mesh as mesh_mod
from paddle_tpu.incubate.distributed.models.moe import (ExpertLayer,
                                                        MoELayer)
from paddle_tpu.incubate.distributed.models.moe.gate import GShardGate
from paddle_tpu.kernels.moe_dispatch import (fused_moe_combine,
                                             fused_moe_dispatch,
                                             reference_moe_combine,
                                             reference_moe_dispatch)


@pytest.fixture(autouse=True)
def reset_mesh():
    saved = (mesh_mod._global_mesh, mesh_mod._hcg)
    yield
    mesh_mod._global_mesh, mesh_mod._hcg = saved


def _np(t):
    return np.asarray(t._value if hasattr(t, "_value") else t)


def _rand(rng, *shape):
    import jax.numpy as jnp
    return jnp.asarray(rng.standard_normal(shape).astype(np.float32))


# ---------------------------------------------------------------------------
# kernel == reference (interpret mode)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("S,E,C,K,kind", [
    (16, 4, 5, 2, "gshard"),     # plain top-2
    (13, 4, 2, 2, "renorm"),     # ragged token count + tight capacity
    (7, 3, 1, 1, "switch"),      # top-1, capacity-1 overflow drops
    (32, 8, 3, 2, "naive"),      # raw-logit combine weights
    (5, 4, 20, 2, "gshard"),     # capacity >> tokens (no drops)
    (130, 4, 40, 2, "gshard"),   # crosses the 128-token block boundary
])
def test_fused_dispatch_matches_reference(S, E, C, K, kind):
    rng = np.random.default_rng(S * 31 + E)
    M = 8
    x = _rand(rng, S, M)
    gw = _rand(rng, M, E)
    gb = _rand(rng, E) * 0.1
    ref = reference_moe_dispatch(x, gw, gb, num_expert=E, capacity=C,
                                 top_k=K, gate_kind=kind)
    got = fused_moe_dispatch(x, gw, gb, num_expert=E, capacity=C,
                             top_k=K, gate_kind=kind)
    for name, a, b in zip(("expert_in", "comb_idx", "val", "me", "ce"),
                          got, ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-5, err_msg=name)


def test_fused_dispatch_uneven_expert_load():
    """A heavily skewed gate (one hot expert) must produce identical
    drop/slot behavior — the priority-major counter walk is where a
    fused implementation would most plausibly diverge."""
    import jax.numpy as jnp
    rng = np.random.default_rng(7)
    S, M, E, C, K = 24, 8, 4, 3, 2
    x = _rand(rng, S, M)
    gw = _rand(rng, M, E) * 0.01
    gb = jnp.asarray([4.0, 0.0, -1.0, -1.0], jnp.float32)  # expert 0 hot
    ref = reference_moe_dispatch(x, gw, gb, num_expert=E, capacity=C,
                                 top_k=K, gate_kind="gshard")
    got = fused_moe_dispatch(x, gw, gb, num_expert=E, capacity=C,
                             top_k=K, gate_kind="gshard")
    # expert 0 overflows: exactly C of its >= C assignments survive
    drops = int((np.asarray(ref[1]) == E * C).sum())
    assert drops > 0, "fixture must actually overflow capacity"
    for a, b in zip(got, ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-5)


def test_fused_combine_matches_reference_with_drops():
    import jax.numpy as jnp
    rng = np.random.default_rng(3)
    S, M, E, C, K = 12, 8, 4, 2, 2
    eo = _rand(rng, E * C, M)
    val = jnp.abs(_rand(rng, S, K))
    comb = rng.integers(0, E * C + 1, (S, K)).astype(np.int32)  # incl. drop
    comb = jnp.asarray(comb)
    want = reference_moe_combine(eo, val, comb)
    got = fused_moe_combine(eo, val, comb)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_fused_gradients_match_reference():
    import jax
    import jax.numpy as jnp
    rng = np.random.default_rng(11)
    S, M, E, C, K = 12, 8, 4, 3, 2
    x = _rand(rng, S, M)
    gw = _rand(rng, M, E)
    gb = jnp.zeros((E,), jnp.float32)

    def loss(dispatch, combine, x, gw, gb):
        ei, comb, val, me, ce = dispatch(x, gw, gb, num_expert=E,
                                         capacity=C, top_k=K,
                                         gate_kind="gshard")
        eo = jnp.tanh(ei.reshape(E * C, M))
        y = combine(eo, val, comb)
        return jnp.sum(y * y) + jnp.sum(me * ce) * E

    gf = jax.grad(lambda *a: loss(fused_moe_dispatch, fused_moe_combine,
                                  *a), argnums=(0, 1, 2))(x, gw, gb)
    gr = jax.grad(lambda *a: loss(reference_moe_dispatch,
                                  reference_moe_combine, *a),
                  argnums=(0, 1, 2))(x, gw, gb)
    for a, b, n in zip(gf, gr, ("x", "gate_w", "gate_b")):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-6, err_msg=n)


# ---------------------------------------------------------------------------
# MoELayer(fused_dispatch=True) + ep_moe_ffn(fused_dispatch=True)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("gate,train", [
    ({"type": "gshard", "top_k": 2}, False),
    ({"type": "naive", "top_k": 2}, True),
    ({"type": "switch", "top_k": 1}, False),
])
def test_moe_layer_fused_matches_reference(gate, train):
    paddle.seed(0)
    E, M, S = 4, 8, 16
    experts = [ExpertLayer(M, 16) for _ in range(E)]
    ref = MoELayer(M, experts, gate=dict(gate), capacity_factor=1.0)
    fz = MoELayer(M, experts, gate=dict(gate), capacity_factor=1.0,
                  fused_dispatch=True)
    fz.gate.gate.weight.set_value(_np(ref.gate.gate.weight))
    fz.gate.gate.bias.set_value(_np(ref.gate.gate.bias))
    (ref.train(), fz.train()) if train else (ref.eval(), fz.eval())
    rng = np.random.default_rng(3)
    x = paddle.to_tensor(rng.standard_normal((S, M)).astype(np.float32))
    np.testing.assert_allclose(_np(fz(x)), _np(ref(x)),
                               rtol=1e-5, atol=1e-5)


def test_moe_layer_fused_falls_back_on_random_gate():
    """GShard random routing draws framework RNG the kernel cannot
    replicate — the fused layer must take the reference path in
    training mode (and the fused path in eval)."""
    paddle.seed(1)
    E, M = 4, 8
    moe = MoELayer(M, [ExpertLayer(M, 16) for _ in range(E)],
                   gate={"type": "gshard", "top_k": 2},
                   fused_dispatch=True)
    moe.train()
    assert moe._fused_gate_kind() is None
    moe.eval()
    assert moe._fused_gate_kind() == "gshard"


def test_moe_layer_fused_aux_loss_matches():
    """Training with fused dispatch keeps the GShard load-balance loss —
    rebuilt from the kernel's me/ce outputs, same value as the gate's."""
    paddle.seed(2)
    E, M, S = 4, 8, 16
    experts = [ExpertLayer(M, 16) for _ in range(E)]
    g1 = GShardGate(M, E, 1, topk=2, random_routing=False)
    g2 = GShardGate(M, E, 1, topk=2, random_routing=False)
    g2.gate.weight.set_value(_np(g1.gate.weight))
    g2.gate.bias.set_value(_np(g1.gate.bias))
    ref = MoELayer(M, experts, gate=g1, capacity_factor=2.0)
    fz = MoELayer(M, experts, gate=g2, capacity_factor=2.0,
                  fused_dispatch=True)
    ref.train()
    fz.train()
    rng = np.random.default_rng(5)
    x = paddle.to_tensor(rng.standard_normal((S, M)).astype(np.float32))
    np.testing.assert_allclose(_np(fz(x)), _np(ref(x)), rtol=1e-5,
                               atol=1e-5)
    a1 = float(_np(ref.gate.get_loss()))
    a2 = float(_np(fz.gate.get_loss()))
    np.testing.assert_allclose(a2, a1, rtol=1e-5)


def test_moe_trajectory_equivalence_fused_vs_unfused():
    """Short train run: fused and unfused layers from identical init
    follow the same loss trajectory (the custom-VJP backward is the
    reference's, so steps match to float tolerance)."""
    from paddle_tpu import optimizer

    def build(fused):
        paddle.seed(42)
        E, M = 4, 8
        gate = GShardGate(M, E, 1, topk=2, random_routing=False)
        return MoELayer(M, [ExpertLayer(M, 16) for _ in range(E)],
                        gate=gate, capacity_factor=1.5,
                        fused_dispatch=fused)

    def run(layer):
        layer.train()
        opt = optimizer.AdamW(learning_rate=1e-2,
                              parameters=layer.parameters())
        rng = np.random.default_rng(9)
        losses = []
        for _ in range(4):
            x = paddle.to_tensor(
                rng.standard_normal((16, 8)).astype(np.float32))
            out = layer(x)
            loss = ops.mean(out * out) + 0.01 * layer.gate.get_loss()
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(_np(loss)))
        return losses

    l_ref = run(build(False))
    l_fused = run(build(True))
    np.testing.assert_allclose(l_fused, l_ref, rtol=1e-4)


def test_ep_moe_ffn_fused_matches_unfused():
    import jax.numpy as jnp
    from paddle_tpu.incubate.distributed.models.moe import ep_moe_ffn
    rng = np.random.default_rng(17)
    E, S, M, H = 4, 24, 8, 16
    a = dict(ep_axis=None, num_expert=E, capacity=8, top_k=2)
    args = (_rand(rng, S, M), _rand(rng, M, E) * 0.5,
            _rand(rng, E) * 0.1, _rand(rng, E, M, H) * 0.2,
            _rand(rng, E, H) * 0.1, _rand(rng, E, H, M) * 0.2,
            _rand(rng, E, M) * 0.1)
    y_ref = ep_moe_ffn(*args, **a)
    y_fused = ep_moe_ffn(*args, fused_dispatch=True, **a)
    np.testing.assert_allclose(np.asarray(y_fused), np.asarray(y_ref),
                               rtol=1e-5, atol=1e-5)


# ---------------------------------------------------------------------------
# cost model: fused pricing + PTCS004 + the all_to_all_q what-if
# ---------------------------------------------------------------------------

def _stage_jaxprs(S=4096, M=512, E=16, K=2):
    import jax
    import jax.numpy as jnp
    C = int(1.2 * K * S / E)
    sds = jax.ShapeDtypeStruct
    f32 = jnp.float32
    avals = (sds((S, M), f32), sds((M, E), f32), sds((E,), f32),
             sds((E * C, M), f32))

    def stage(dispatch, combine):
        def run(x, gw, gb, eo):
            ei, comb, val, _, _ = dispatch(x, gw, gb, num_expert=E,
                                           capacity=C, top_k=K,
                                           gate_kind="renorm")
            return ei, combine(eo, val, comb)
        return jax.make_jaxpr(run)(*avals)

    return (stage(reference_moe_dispatch, reference_moe_combine),
            stage(fused_moe_dispatch, fused_moe_combine))


def test_ptcs004_fires_on_unfused_clean_on_fused():
    from paddle_tpu.analysis.passes.cost import _moe_fusion_opportunities
    ju, jf = _stage_jaxprs()
    fires = _moe_fusion_opportunities(ju.jaxpr)
    assert fires and fires[0]["ratio"] > 2.0, fires
    assert _moe_fusion_opportunities(jf.jaxpr) == []


def test_pallas_call_priced_as_fused_anchor():
    """The cost model charges a pallas_call body FLOPs × grid but HBM
    only for the call's operands/results — so the fused dispatch prices
    strictly less HBM (and less step time on a v5e) than the identical
    unfused chain."""
    from paddle_tpu.analysis.passes.cost import estimate_jaxpr_cost
    from paddle_tpu.observability.instrument import chip_specs
    chip = chip_specs("v5e")
    ju, jf = _stage_jaxprs()
    cu = estimate_jaxpr_cost(ju, chip=chip)
    cf = estimate_jaxpr_cost(jf, chip=chip)
    assert "pallas_call" in cf.by_prim and "pallas_call" not in cu.by_prim
    assert cf.hbm_bytes < cu.hbm_bytes
    assert cf.step_ms < cu.step_ms, (cf.step_ms, cu.step_ms)


def test_ptcs004_diagnostic_through_analyzer():
    """End to end through the registered pass: analyzing the unfused
    dispatch stage emits exactly one PTCS004 info; the fused stage none."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.analysis import ProgramAnalyzer
    S, M, E, K = 4096, 512, 16, 2
    C = int(1.2 * K * S / E)
    sds = jax.ShapeDtypeStruct
    f32 = jnp.float32

    from paddle_tpu.ops._dispatch import unwrap

    def unfused(x, gw, gb, eo):
        x, gw, gb, eo = (unwrap(t) for t in (x, gw, gb, eo))
        ei, comb, val, _, _ = reference_moe_dispatch(
            x, gw, gb, num_expert=E, capacity=C, top_k=K,
            gate_kind="renorm")
        return ei, reference_moe_combine(eo, val, comb)

    def fused(x, gw, gb, eo):
        x, gw, gb, eo = (unwrap(t) for t in (x, gw, gb, eo))
        ei, comb, val, _, _ = fused_moe_dispatch(
            x, gw, gb, num_expert=E, capacity=C, top_k=K,
            gate_kind="renorm")
        return ei, fused_moe_combine(eo, val, comb)

    avals = (sds((S, M), f32), sds((M, E), f32), sds((E,), f32),
             sds((E * C, M), f32))
    rep_u = ProgramAnalyzer().analyze(unfused, *avals,
                                      name="moe.unfused", emit=False)
    rep_f = ProgramAnalyzer().analyze(fused, *avals, name="moe.fused",
                                      emit=False)
    codes_u = [d.code for d in rep_u.diagnostics]
    codes_f = [d.code for d in rep_f.diagnostics]
    assert codes_u.count("PTCS004") == 1, codes_u
    assert "PTCS004" not in codes_f, codes_f


def test_expert_all_to_all_priced_with_int8_whatif():
    """The expert all_to_all inside the shard-mapped ep_moe_ffn carries
    the int8 wire what-if (PR 9's ``all_to_all_q`` pricing): the cost
    summary's compressed bytes are ~4x below the f32 wire, and a
    ``wire_dtype='int8'`` run of the SAME program prices at the what-if
    — the auto-enable loop's decision inputs."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu._jax_compat import shard_map
    from jax.sharding import PartitionSpec as P
    from paddle_tpu.analysis.passes.cost import estimate_jaxpr_cost
    from paddle_tpu.distributed.mesh import HybridCommunicateGroup
    from paddle_tpu.incubate.distributed.models.moe import ep_moe_ffn

    mesh_mod._global_mesh, mesh_mod._hcg = None, None
    hcg = HybridCommunicateGroup(dp_degree=1, sharding_degree=8)
    mesh = hcg.mesh
    ep = 8
    # M sized so quantized rows land exactly on the 256-element chunk
    # grid — the what-if formula does not model sub-chunk padding
    E, S, M, H = 8, 64, 64, 32
    S_local = S // ep
    sds = jax.ShapeDtypeStruct
    f32 = jnp.float32

    def run(wire):
        def prog(xl, gw, gb, w1l, b1l, w2l, b2l):
            return ep_moe_ffn(xl, gw, gb, w1l, b1l, w2l, b2l,
                              ep_axis="sharding", num_expert=E,
                              capacity=S_local, top_k=2,
                              wire_dtype=wire)
        f = shard_map(
            prog, mesh=mesh,
            in_specs=(P("sharding"), P(), P(), P("sharding"),
                      P("sharding"), P("sharding"), P("sharding")),
            out_specs=P("sharding"), check_vma=False)
        j = jax.make_jaxpr(f)(
            sds((S, M), f32), sds((M, E), f32), sds((E,), f32),
            sds((E, M, H), f32), sds((E, H), f32), sds((E, H, M), f32),
            sds((E, M), f32))
        sizes = {k: int(v) for k, v in dict(mesh.shape).items()}
        return estimate_jaxpr_cost(j, axis_sizes=sizes)

    fp = run(None)
    assert fp.comm_bytes > 0
    assert fp.comm_bytes_int8 < fp.comm_bytes / 3.0, \
        (fp.comm_bytes, fp.comm_bytes_int8)
    i8 = run("int8")
    # the compressed program's ACTUAL wire (int8 shards + f32 scales)
    # lands within ~10% of the uncompressed program's int8 what-if
    assert i8.comm_bytes < fp.comm_bytes / 3.0
    np.testing.assert_allclose(i8.comm_bytes, fp.comm_bytes_int8,
                               rtol=0.15)


# ---------------------------------------------------------------------------
# moe_utils: count diagnostics name the offending expert
# ---------------------------------------------------------------------------

def test_global_scatter_count_mismatch_names_expert():
    from paddle_tpu.distributed.utils import global_gather, global_scatter
    x = paddle.to_tensor(
        np.random.default_rng(0).standard_normal((6, 4)).astype(np.float32))
    lc = paddle.to_tensor(np.array([2, 4], np.int64))
    gc = paddle.to_tensor(np.array([3, 3], np.int64))
    for fn in (global_scatter, global_gather):
        with pytest.raises(ValueError) as ei:
            fn(x, lc, gc)
        msg = str(ei.value)
        assert "expert bin 0" in msg, msg
        assert "2" in msg and "3" in msg

    # totals wrong: the error names the first diverging bin too
    lc2 = paddle.to_tensor(np.array([2, 3], np.int64))
    with pytest.raises(ValueError) as ei:
        global_scatter(x, lc2, lc2)
    assert "sums to 5 rows but x has 6" in str(ei.value)

    # shape mismatch between the two count vectors
    with pytest.raises(ValueError) as ei:
        global_scatter(x, lc, paddle.to_tensor(np.array([6], np.int64)))
    assert "expert bins" in str(ei.value)

    # the happy path still round-trips
    y = global_scatter(x, lc, lc)
    z = global_gather(y, lc, lc)
    np.testing.assert_allclose(_np(z), _np(x))
