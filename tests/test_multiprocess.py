"""Real multi-process operation: the launcher's endpoint exchange, a
2-process x 4-device jax.distributed world through init_parallel_env,
per-process mesh-axis ranks, store-backed object collectives, and the
hard error on single-controller-only eager collectives.

Parity model: reference test_launch_coverage / test_collective_* which run
real worker subprocesses over loopback (launch/controllers/master.py,
distributed/parallel.py:108).
"""
import os
import socket
import subprocess
import sys
import textwrap

import numpy as np
import pytest

# real multi-process jax worlds are the slowest tier of the
# suite; tier-1 (-m 'not slow') relies on the in-proc elastic
# + spawn coverage in test_elastic_relaunch.py instead
pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = textwrap.dedent("""
    import os, sys
    sys.path.insert(0, "__REPO__")
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    import paddle_tpu.distributed as dist
    from paddle_tpu.distributed import env as env_mod
    from paddle_tpu.distributed import mesh as mesh_mod

    env = dist.init_parallel_env()
    rank, world = env.rank, env.world_size
    assert world == 2, world
    assert jax.process_count() == 2, jax.process_count()
    assert len(jax.devices()) == 8, len(jax.devices())

    # global computation over the multi-process mesh
    mesh = mesh_mod.get_global_mesh()
    sh = NamedSharding(mesh, P("dp"))
    arr = jax.make_array_from_process_local_data(
        sh, np.full((4,), float(rank + 1), np.float32))
    total = float(jax.jit(jnp.sum)(arr))
    assert abs(total - 12.0) == 0.0, total

    # per-process mesh-axis ranks are real coordinates now
    g = dist.get_group()
    expect = 0 if rank == 0 else 4
    assert g.rank == expect, (rank, g.rank)

    # store-backed object collectives
    objs = [{"v": 41}, None] if rank == 0 else [None, None]
    dist.broadcast_object_list(objs, src=0)
    assert objs[0] == {"v": 41}, objs

    out = [None]
    dist.scatter_object_list(out, in_object_list=["a", "b"] if rank == 0
                             else None, src=0)
    assert out == ["a" if rank == 0 else "b"], (rank, out)

    gathered = []
    dist.all_gather_object(gathered, f"r{rank}")
    assert gathered == ["r0", "r1"], gathered

    dist.barrier()

    # single-controller-only eager collectives hard-error
    try:
        dist.all_to_all([], [jnp.zeros(2)])
    except NotImplementedError as e:
        assert "single-controller" in str(e)
    else:
        raise SystemExit("all_to_all should have raised")

    print(f"MP_WORKER_OK rank={rank} total={total}", flush=True)
""").replace("__REPO__", REPO)


def _run_launch(tmp_path, extra_args, env_extra, n_expect):
    worker = tmp_path / "mp_worker.py"
    worker.write_text(WORKER)
    log_dir = tmp_path / "logs"
    env = {**os.environ,
           "JAX_PLATFORMS": "cpu",
           "XLA_FLAGS": "--xla_force_host_platform_device_count=4"}
    # drop any stale contract vars from the pytest process
    for k in list(env):
        if k.startswith("PADDLE_"):
            env.pop(k)
    env.update(env_extra)
    proc = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--log_dir", str(log_dir)] + extra_args + [str(worker)],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=300)
    logs = ""
    if log_dir.exists():
        for f in sorted(log_dir.iterdir()):
            logs += f.read_text()
    assert proc.returncode == 0, (proc.stdout, proc.stderr, logs)
    assert logs.count("MP_WORKER_OK") == n_expect, logs
    return logs


def test_launch_2proc_4dev_world(tmp_path):
    """Single-node launcher: 2 processes x 4 CPU devices = one 8-device
    jax.distributed world; collectives + ranks verified in-worker."""
    logs = _run_launch(tmp_path, ["--nproc_per_node", "2"], {}, 2)
    assert "rank=0" in logs and "rank=1" in logs


def test_launch_master_endpoint_exchange(tmp_path):
    """Two launcher invocations (--master, nnodes=2) exchange endpoints
    through the native TCPStore and form ONE world."""
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    master = f"127.0.0.1:{port}"

    worker = tmp_path / "mp_worker.py"
    worker.write_text(WORKER)
    log0, log1 = tmp_path / "l0", tmp_path / "l1"
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "XLA_FLAGS": "--xla_force_host_platform_device_count=4"}
    for k in list(env):
        if k.startswith("PADDLE_"):
            env.pop(k)
    common = [sys.executable, "-m", "paddle_tpu.distributed.launch",
              "--nproc_per_node", "1", "--nnodes", "2",
              "--master", master]
    p0 = subprocess.Popen(common + ["--node_rank", "0", "--log_dir",
                                    str(log0), str(worker)],
                          env=env, cwd=REPO)
    p1 = subprocess.Popen(common + ["--node_rank", "1", "--log_dir",
                                    str(log1), str(worker)],
                          env=env, cwd=REPO)
    assert p0.wait(timeout=300) == 0
    assert p1.wait(timeout=300) == 0
    logs = ""
    for d in (log0, log1):
        for f in sorted(d.iterdir()):
            logs += f.read_text()
    assert logs.count("MP_WORKER_OK") == 2, logs
    assert "rank=0" in logs and "rank=1" in logs
