"""nn/nn.functional round-3 additions vs torch (cpu) or numpy oracles."""
import numpy as np
import pytest
import torch
import torch.nn.functional as tF

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F

rng = np.random.default_rng(0)


def _t(a):
    return paddle.to_tensor(np.asarray(a))


def test_pairwise_distance_vs_torch():
    x = rng.standard_normal((5, 7)).astype(np.float32)
    y = rng.standard_normal((5, 7)).astype(np.float32)
    got = nn.PairwiseDistance(p=2.0)(_t(x), _t(y)).numpy()
    want = tF.pairwise_distance(torch.tensor(x), torch.tensor(y)).numpy()
    np.testing.assert_allclose(got, want, rtol=1e-4)


def test_soft_margin_losses_vs_torch():
    x = rng.standard_normal((6, 4)).astype(np.float32)
    y = np.where(rng.random((6, 4)) > 0.5, 1.0, -1.0).astype(np.float32)
    got = F.soft_margin_loss(_t(x), _t(y)).numpy()
    want = tF.soft_margin_loss(torch.tensor(x), torch.tensor(y)).numpy()
    np.testing.assert_allclose(got, want, rtol=1e-5)

    yl = (y > 0).astype(np.float32)
    got = nn.MultiLabelSoftMarginLoss()(_t(x), _t(yl)).numpy()
    want = tF.multilabel_soft_margin_loss(
        torch.tensor(x), torch.tensor(yl)).numpy()
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_multi_margin_loss_vs_torch():
    x = rng.standard_normal((6, 5)).astype(np.float32)
    y = rng.integers(0, 5, 6).astype(np.int64)
    got = nn.MultiMarginLoss()(_t(x), _t(y)).numpy()
    want = tF.multi_margin_loss(torch.tensor(x), torch.tensor(y)).numpy()
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_triplet_with_distance_vs_torch():
    a = rng.standard_normal((4, 8)).astype(np.float32)
    p = rng.standard_normal((4, 8)).astype(np.float32)
    n = rng.standard_normal((4, 8)).astype(np.float32)
    got = nn.TripletMarginWithDistanceLoss(margin=0.7, swap=True)(
        _t(a), _t(p), _t(n)).numpy()
    want = tF.triplet_margin_with_distance_loss(
        torch.tensor(a), torch.tensor(p), torch.tensor(n), margin=0.7,
        swap=True).numpy()
    np.testing.assert_allclose(got, want, rtol=1e-4)


def test_softmax2d_and_inplace_acts():
    x = rng.standard_normal((2, 3, 4, 4)).astype(np.float32)
    got = nn.Softmax2D()(_t(x)).numpy()
    want = tF.softmax(torch.tensor(x), dim=1).numpy()
    np.testing.assert_allclose(got, want, rtol=1e-5)

    t = _t(x.copy())
    F.softmax_(t, axis=1)
    np.testing.assert_allclose(t.numpy(), want, rtol=1e-5)
    t2 = _t(np.array([-1.0, 2.0], np.float32))
    F.elu_(t2)
    np.testing.assert_allclose(
        t2.numpy(), tF.elu(torch.tensor([-1.0, 2.0])).numpy(), rtol=1e-5)


def test_max_unpool2d_roundtrip_vs_torch():
    x = rng.standard_normal((2, 3, 8, 8)).astype(np.float32)
    tx = torch.tensor(x)
    pooled, idx = tF.max_pool2d(tx, 2, return_indices=True)
    want = tF.max_unpool2d(pooled, idx, 2).numpy()
    got = F.max_unpool2d(_t(pooled.numpy()), _t(idx.numpy().astype(
        np.int64)), kernel_size=2).numpy()
    np.testing.assert_allclose(got, want, rtol=1e-5)
    assert got.shape == (2, 3, 8, 8)


def test_diag_embed_sequence_mask_zeropad():
    v = rng.standard_normal((2, 3)).astype(np.float32)
    got = F.diag_embed(_t(v)).numpy()
    want = torch.diag_embed(torch.tensor(v)).numpy()
    np.testing.assert_allclose(got, want)
    m = F.sequence_mask(_t(np.array([1, 3])), maxlen=4).numpy()
    np.testing.assert_array_equal(m, [[1, 0, 0, 0], [1, 1, 1, 0]])
    z = F.zeropad2d(_t(rng.standard_normal((1, 1, 2, 2))
                       .astype(np.float32)), [1, 0, 0, 2])
    assert z.numpy().shape == (1, 1, 4, 3)


def test_affine_grid_sample_identity_vs_torch():
    x = rng.standard_normal((2, 3, 5, 6)).astype(np.float32)
    theta = np.tile(np.array([[[1.0, 0, 0], [0, 1.0, 0]]], np.float32),
                    (2, 1, 1))
    grid = F.affine_grid(_t(theta), [2, 3, 5, 6], align_corners=True)
    want_grid = tF.affine_grid(torch.tensor(theta), [2, 3, 5, 6],
                               align_corners=True).numpy()
    np.testing.assert_allclose(grid.numpy(), want_grid, atol=1e-6)
    out = F.grid_sample(_t(x), grid, align_corners=True).numpy()
    want = tF.grid_sample(torch.tensor(x), torch.tensor(want_grid),
                          align_corners=True).numpy()
    np.testing.assert_allclose(out, want, rtol=1e-4, atol=1e-5)


def test_temporal_shift_shapes_and_content():
    x = np.arange(2 * 2 * 4 * 1 * 1, dtype=np.float32) \
        .reshape(4, 4, 1, 1)  # nt=4 (n=2,seg=2), c=4
    out = F.temporal_shift(_t(x), seg_num=2, shift_ratio=0.25).numpy()
    assert out.shape == x.shape
    # first quarter channels shift backward: (n, seg 0) takes (n, seg 1)
    # and the final segment zero-fills (nt layout is n*seg + s)
    np.testing.assert_allclose(out[0, 0], x[1, 0])
    np.testing.assert_allclose(out[1, 0], 0.0)


def test_hsigmoid_loss_trains():
    feat, classes = 8, 6
    layer = nn.HSigmoidLoss(feat, classes)
    x = _t(rng.standard_normal((10, feat)).astype(np.float32))
    y = _t(rng.integers(0, classes, 10).astype(np.int64))
    loss = layer(x, y).mean()
    assert float(loss.numpy()) > 0
    loss.backward()
    assert layer.weight.grad is not None


def test_margin_cross_entropy_reduces_to_ce_at_zero_margin():
    lg = (rng.standard_normal((5, 7)) * 0.3).astype(np.float32)
    lg = lg / np.linalg.norm(lg, axis=1, keepdims=True)  # cosine-like
    y = rng.integers(0, 7, 5).astype(np.int64)
    got = F.margin_cross_entropy(_t(lg), _t(y), margin1=1.0, margin2=0.0,
                                 margin3=0.0, scale=1.0).numpy()
    want = tF.cross_entropy(torch.tensor(lg), torch.tensor(y)).numpy()
    np.testing.assert_allclose(got, want, rtol=1e-4)


def test_rnnt_loss_vs_torchaudio_or_bruteforce():
    """Small lattice checked against exhaustive path enumeration."""
    B, T, U, V = 1, 3, 2, 4
    logits = rng.standard_normal((B, T, U + 1, V)).astype(np.float32)
    labels = np.array([[1, 2]], np.int64)
    got = float(F.rnnt_loss(_t(logits), _t(labels),
                            _t(np.array([T], np.int64)),
                            _t(np.array([U], np.int64)),
                            reduction="none").numpy())

    # brute force: all monotonic alignments of 2 labels into 3 frames
    import itertools
    import scipy.special as sp
    lp = torch.log_softmax(torch.tensor(logits), dim=-1).numpy()[0]
    total = []
    # a path = sequence of moves from (0,0) to (T-1,U) + final blank;
    # at (t,u): blank -> (t+1,u), label -> (t,u+1)
    def walk(t, u, acc):
        if t == T - 1 and u == U:
            total.append(acc + lp[t, u, 0])  # final blank
            return
        if t < T - 1:
            walk(t + 1, u, acc + lp[t, u, 0])
        if u < U:
            walk(t, u + 1, acc + lp[t, u, labels[0, u]])
    walk(0, 0, 0.0)
    want = -sp.logsumexp(np.array(total))
    np.testing.assert_allclose(got, want, rtol=1e-4)


def test_gather_tree_backtrace():
    # T=2, B=1, beam=2: step0 ids [[5, 6]], step1 ids [[7, 8]] with
    # parents [[0,0],[1,0]] -> beam0 path = 5 (parent of 7 is beam 1->6? )
    ids = np.array([[[5, 6]], [[7, 8]]], np.int64)
    parents = np.array([[[0, 0]], [[1, 0]]], np.int64)
    out = F.gather_tree(_t(ids), _t(parents)).numpy()
    # beam 0 at t=1 has parent 1 -> its t=0 token is 6
    np.testing.assert_array_equal(out[:, 0, 0], [6, 7])
    np.testing.assert_array_equal(out[:, 0, 1], [5, 8])


def test_birnn_concat_shapes():
    cell_fw = nn.GRUCell(4, 6)
    cell_bw = nn.GRUCell(4, 6)
    rnn = nn.BiRNN(cell_fw, cell_bw)
    x = _t(rng.standard_normal((2, 5, 4)).astype(np.float32))
    out, (fw, bw) = rnn(x)
    assert out.numpy().shape == (2, 5, 12)


def test_beam_search_decoder_greedy():
    class ToyCell(nn.Layer):
        def __init__(self):
            super().__init__()
            self.lin = nn.Linear(1, 5)

        def forward(self, tok, states):
            x = paddle.cast(tok, "float32").reshape([-1, 1])
            return self.lin(x * 0.1), states

    dec = nn.BeamSearchDecoder(ToyCell(), start_token=0, end_token=4,
                               beam_size=2)
    ids, _ = nn.dynamic_decode(dec, inits=None, max_step_num=3,
                               batch_size=2)
    assert ids.numpy().shape[0] == 2 and ids.numpy().shape[2] == 2


def test_rnnt_loss_layer_batch():
    B, T, U, V = 2, 4, 3, 5
    logits = rng.standard_normal((B, T, U + 1, V)).astype(np.float32)
    labels = rng.integers(1, V, (B, U)).astype(np.int64)
    loss = nn.RNNTLoss()(_t(logits), _t(labels),
                         _t(np.full(B, T, np.int64)),
                         _t(np.full(B, U, np.int64)))
    assert float(loss.numpy()) > 0


def test_hsigmoid_paths_distinct_for_non_power_of_two():
    """num_classes=6: every class must map to a distinct root-to-leaf
    path (the review found clipping aliased classes 4 and 5)."""
    n = 6
    paths = {}
    for c in range(n):
        idx = c + (n - 1)
        path = []
        while idx > 0:
            path.append(((idx - 1) // 2, idx % 2 == 1))
            idx = (idx - 1) // 2
        assert all(node < n - 1 for node, _ in path)
        paths[c] = tuple(path)
    assert len(set(paths.values())) == n


def test_birnn_sequence_length_masks_padding():
    """The backward pass over a padded sample must start at its true
    last step: output at t=0 equals a no-padding run's output."""
    cell_fw, cell_bw = nn.GRUCell(3, 4), nn.GRUCell(3, 4)
    rnn = nn.BiRNN(cell_fw, cell_bw)
    x_short = rng.standard_normal((1, 2, 3)).astype(np.float32)
    x_padded = np.concatenate(
        [x_short, np.zeros((1, 3, 3), np.float32)], axis=1)
    out_pad, (fw_pad, bw_pad) = rnn(
        _t(x_padded), sequence_length=_t(np.array([2], np.int64)))
    out_ref, (fw_ref, bw_ref) = rnn(_t(x_short))
    np.testing.assert_allclose(out_pad.numpy()[:, :2], out_ref.numpy(),
                               rtol=1e-5, atol=1e-6)
    # final states must be padding-free too (review finding): the state
    # freezes at each sample's true last step
    np.testing.assert_allclose(fw_pad.numpy(), fw_ref.numpy(),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(bw_pad.numpy(), bw_ref.numpy(),
                               rtol=1e-5, atol=1e-6)
    # padded region of outputs is zeroed
    np.testing.assert_allclose(out_pad.numpy()[:, 2:], 0.0)


def test_max_unpool2d_nhwc():
    x = rng.standard_normal((2, 3, 8, 8)).astype(np.float32)
    tx = torch.tensor(x)
    pooled, idx = tF.max_pool2d(tx, 2, return_indices=True)
    want = tF.max_unpool2d(pooled, idx, 2).numpy().transpose(0, 2, 3, 1)
    got = F.max_unpool2d(
        _t(pooled.numpy().transpose(0, 2, 3, 1)),
        _t(idx.numpy().astype(np.int64).transpose(0, 2, 3, 1)),
        kernel_size=2, data_format="NHWC").numpy()
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_sparse_attention_matches_dense_and_traces():
    B, H, S, D = 1, 1, 4, 8
    q = rng.standard_normal((B, H, S, D)).astype(np.float32)
    k = rng.standard_normal((B, H, S, D)).astype(np.float32)
    v = rng.standard_normal((B, H, S, D)).astype(np.float32)
    # full pattern via CSR: every row attends everywhere
    off = np.tile(np.arange(0, (S + 1) * S, S, dtype=np.int32)[:S + 1],
                  (B, H, 1))
    cols = np.tile(np.tile(np.arange(S, dtype=np.int32), S), (B, H, 1))
    got = F.sparse_attention(_t(q), _t(k), _t(v), _t(off),
                             _t(cols)).numpy()
    want = tF.scaled_dot_product_attention(
        torch.tensor(q), torch.tensor(k), torch.tensor(v)).numpy()
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)
    # traces under static capture (the reviewed crash)
    from paddle_tpu import static
    static.enable_static()
    try:
        main = static.Program()
        with static.program_guard(main):
            qs = static.data("q", [B, H, S, D], "float32")
            out = F.sparse_attention(qs, _t(k), _t(v), _t(off), _t(cols))
        exe = static.Executor()
        (res,) = exe.run(main, feed={"q": q}, fetch_list=[out])
        np.testing.assert_allclose(res, want, rtol=1e-4, atol=1e-5)
    finally:
        static.disable_static()



def test_hsigmoid_custom_tree_and_rnnt_fastemit_guard():
    # custom path tables: single internal node, classes split on bit
    x = _t(rng.standard_normal((4, 3)).astype(np.float32))
    w = _t(rng.standard_normal((1, 3)).astype(np.float32))
    pt = _t(np.array([[0], [0], [0], [0]], np.int64))
    pc = _t(np.array([[1], [1], [0], [0]], np.int64))
    loss = F.hsigmoid_loss(x, None, 2, w, path_table=pt, path_code=pc)
    logits = x.numpy() @ w.numpy().T
    want = np.log1p(np.exp(-np.array([1, 1, -1, -1])[:, None] * logits))
    np.testing.assert_allclose(loss.numpy(), want, rtol=1e-5)
    with pytest.raises(ValueError, match="together"):
        F.hsigmoid_loss(x, None, 2, w, path_table=pt)
    with pytest.raises(NotImplementedError, match="fastemit"):
        F.rnnt_loss(_t(np.zeros((1, 2, 2, 3), np.float32)),
                    _t(np.zeros((1, 1), np.int64)),
                    _t(np.array([2], np.int64)),
                    _t(np.array([1], np.int64)), fastemit_lambda=0.1)
    with pytest.raises(NotImplementedError, match="reflection"):
        F.grid_sample(_t(np.zeros((1, 1, 2, 2), np.float32)),
                      _t(np.zeros((1, 2, 2, 2), np.float32)),
                      padding_mode="reflection")
