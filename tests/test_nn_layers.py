"""nn.Layer corpus tests (parity model: reference unittests for nn layers)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F


def test_linear_matches_numpy():
    l = nn.Linear(4, 3)
    x = paddle.to_tensor(np.random.rand(5, 4).astype("float32"))
    out = l(x)
    ref = x.numpy() @ l.weight.numpy() + l.bias.numpy()
    np.testing.assert_allclose(out.numpy(), ref, rtol=1e-5)


def test_conv2d_matches_torch_free_reference():
    # oracle: explicit im2col conv
    np.random.seed(0)
    x = np.random.rand(1, 2, 5, 5).astype("float32")
    w = np.random.rand(3, 2, 3, 3).astype("float32")
    out = F.conv2d(paddle.to_tensor(x), paddle.to_tensor(w), padding=1)
    ref = np.zeros((1, 3, 5, 5), "float32")
    xp = np.pad(x, [(0, 0), (0, 0), (1, 1), (1, 1)])
    for oc in range(3):
        for i in range(5):
            for j in range(5):
                ref[0, oc, i, j] = np.sum(xp[0, :, i:i + 3, j:j + 3] * w[oc])
    np.testing.assert_allclose(out.numpy(), ref, rtol=1e-4, atol=1e-5)


def test_conv2d_grad_flows():
    conv = nn.Conv2D(3, 4, 3, padding=1)
    x = paddle.to_tensor(np.random.rand(2, 3, 8, 8).astype("float32"))
    loss = conv(x).sum()
    loss.backward()
    assert conv.weight.grad is not None
    assert conv.weight.grad.shape == [4, 3, 3, 3]


def test_conv2d_transpose_shape():
    x = paddle.to_tensor(np.random.rand(1, 4, 8, 8).astype("float32"))
    ct = nn.Conv2DTranspose(4, 2, 2, stride=2)
    assert ct(x).shape == [1, 2, 16, 16]


def test_batchnorm_train_eval():
    bn = nn.BatchNorm2D(3)
    x = paddle.to_tensor((np.random.rand(4, 3, 8, 8) * 5 + 2).astype("float32"))
    bn.train()
    out = bn(x)
    m = out.numpy().mean(axis=(0, 2, 3))
    np.testing.assert_allclose(m, np.zeros(3), atol=1e-4)
    # running stats moved toward batch stats
    assert not np.allclose(bn._mean.numpy(), np.zeros(3))
    bn.eval()
    out2 = bn(x)
    assert out2.shape == [4, 3, 8, 8]


def test_layernorm():
    ln = nn.LayerNorm(16)
    x = paddle.to_tensor(np.random.rand(4, 16).astype("float32"))
    out = ln(x).numpy()
    np.testing.assert_allclose(out.mean(-1), np.zeros(4), atol=1e-5)
    np.testing.assert_allclose(out.std(-1), np.ones(4), atol=1e-2)


def test_dropout_modes():
    d = nn.Dropout(0.5)
    x = paddle.to_tensor(np.ones((100, 100), "float32"))
    d.train()
    y = d(x)
    frac_zero = float((y.numpy() == 0).mean())
    assert 0.4 < frac_zero < 0.6
    d.eval()
    np.testing.assert_allclose(d(x).numpy(), x.numpy())


def test_embedding():
    emb = nn.Embedding(10, 4, padding_idx=0)
    idx = paddle.to_tensor(np.array([[1, 2, 0]]))
    out = emb(idx)
    assert out.shape == [1, 3, 4]
    np.testing.assert_allclose(out.numpy()[0, 2], np.zeros(4))


def test_mha_self_attention():
    mha = nn.MultiHeadAttention(16, 4)
    x = paddle.to_tensor(np.random.rand(2, 5, 16).astype("float32"))
    out = mha(x)
    assert out.shape == [2, 5, 16]


def test_transformer_encoder_grad():
    enc = nn.TransformerEncoder(
        nn.TransformerEncoderLayer(16, 2, 32, dropout=0.0), 2)
    x = paddle.to_tensor(np.random.rand(2, 6, 16).astype("float32"),
                         stop_gradient=False)
    out = enc(x)
    out.sum().backward()
    assert x.grad is not None
    n_with_grad = sum(1 for p in enc.parameters() if p.grad is not None)
    assert n_with_grad == len(enc.parameters())


def test_lstm_shapes():
    lstm = nn.LSTM(8, 16, num_layers=2, direction="bidirect")
    x = paddle.to_tensor(np.random.rand(3, 7, 8).astype("float32"))
    out, (h, c) = lstm(x)
    assert out.shape == [3, 7, 32]
    assert h.shape == [4, 3, 16]


def test_sequential_and_containers():
    m = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    assert len(m) == 3
    ll = nn.LayerList([nn.Linear(2, 2) for _ in range(3)])
    ll.append(nn.Linear(2, 2))
    assert len(ll) == 4
    ld = nn.LayerDict({"a": nn.Linear(2, 2)})
    assert "a" in ld


def test_state_dict_roundtrip():
    m1 = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    m2 = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    m2.set_state_dict(m1.state_dict())
    x = paddle.to_tensor(np.random.rand(3, 4).astype("float32"))
    np.testing.assert_allclose(m1(x).numpy(), m2(x).numpy(), rtol=1e-6)


def test_losses():
    logits = paddle.to_tensor(np.random.rand(4, 5).astype("float32"))
    labels = paddle.to_tensor(np.array([0, 1, 2, 3]))
    l = nn.CrossEntropyLoss()(logits, labels)
    # numpy oracle
    z = logits.numpy()
    lse = np.log(np.exp(z).sum(-1))
    ref = (lse - z[np.arange(4), [0, 1, 2, 3]]).mean()
    np.testing.assert_allclose(float(l), ref, rtol=1e-5)

    pred = paddle.to_tensor(np.random.rand(4).astype("float32"))
    tgt = paddle.to_tensor(np.random.rand(4).astype("float32"))
    np.testing.assert_allclose(
        float(nn.MSELoss()(pred, tgt)),
        ((pred.numpy() - tgt.numpy()) ** 2).mean(), rtol=1e-5)


def test_cross_entropy_ignore_index():
    logits = paddle.to_tensor(np.random.rand(4, 5).astype("float32"))
    labels = paddle.to_tensor(np.array([0, -100, 2, -100]))
    l = float(nn.CrossEntropyLoss(ignore_index=-100)(logits, labels))
    z = logits.numpy()
    lse = np.log(np.exp(z).sum(-1))
    per = lse - z[np.arange(4), [0, 0, 2, 0]]
    ref = per[[0, 2]].mean()
    np.testing.assert_allclose(l, ref, rtol=1e-5)


def test_activations_match_numpy():
    x = paddle.to_tensor(np.linspace(-3, 3, 13).astype("float32"))
    np.testing.assert_allclose(F.relu(x).numpy(), np.maximum(x.numpy(), 0))
    np.testing.assert_allclose(
        F.sigmoid(x).numpy(), 1 / (1 + np.exp(-x.numpy())), rtol=1e-5)
    sm = F.softmax(x).numpy()
    np.testing.assert_allclose(sm.sum(), 1.0, rtol=1e-6)


def test_clip_grad_by_global_norm():
    p1 = paddle.Parameter(np.ones((2, 2), "float32"))
    p2 = paddle.Parameter(np.ones((3,), "float32"))
    g1 = paddle.to_tensor(np.full((2, 2), 3.0, "float32"))
    g2 = paddle.to_tensor(np.full((3,), 4.0, "float32"))
    clip = nn.ClipGradByGlobalNorm(1.0)
    out = clip([(p1, g1), (p2, g2)])
    total = np.sqrt(sum((g.numpy() ** 2).sum() for _, g in out))
    np.testing.assert_allclose(total, 1.0, rtol=1e-5)
