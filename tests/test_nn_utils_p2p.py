"""paddle.nn.utils (weight/spectral norm hooks, parameter vectors) and
the p2p communication API (P2POp/batch_isend_irecv/isend/irecv)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.nn.utils import (
    parameters_to_vector, remove_weight_norm, spectral_norm,
    vector_to_parameters, weight_norm)


def test_weight_norm_reparameterizes_and_trains():
    lin = nn.Linear(4, 3)
    w0 = lin.weight.numpy().copy()
    weight_norm(lin, name="weight", dim=0)
    names = dict(lin.named_parameters())
    assert "weight_g" in names and "weight_v" in names
    assert "weight" not in names
    # effective weight unchanged by the reparameterization
    np.testing.assert_allclose(lin.weight.numpy(), w0, rtol=1e-5)
    # forward works and grads flow to g and v
    x = paddle.to_tensor(np.random.default_rng(0)
                         .standard_normal((2, 4)).astype(np.float32))
    loss = (lin(x) ** 2).mean()
    loss.backward()
    assert lin.weight_g.grad is not None
    assert lin.weight_v.grad is not None


def test_weight_norm_norm_semantics():
    """||weight[i, :]|| == g[i] after re-scaling g (dim=0 rows)."""
    lin = nn.Linear(5, 2)
    weight_norm(lin, dim=0)
    lin.weight_g.set_value(np.array([2.0, 3.0, 1.0, 0.5, 4.0],
                                    np.float32))
    lin(paddle.to_tensor(np.zeros((1, 5), np.float32)))  # refresh hook
    norms = np.linalg.norm(lin.weight.numpy(), axis=1)
    np.testing.assert_allclose(norms, [2.0, 3.0, 1.0, 0.5, 4.0],
                               rtol=1e-5)


def test_remove_weight_norm_restores_plain_param():
    lin = nn.Linear(4, 3)
    weight_norm(lin)
    w_eff = lin.weight.numpy().copy()
    remove_weight_norm(lin)
    names = dict(lin.named_parameters())
    assert "weight" in names and "weight_g" not in names
    np.testing.assert_allclose(lin.weight.numpy(), w_eff, rtol=1e-5)
    with pytest.raises(ValueError, match="no weight_norm"):
        remove_weight_norm(lin)


def test_weight_norm_dim_none_scalar_g():
    lin = nn.Linear(4, 3)
    w0 = lin.weight.numpy().copy()
    weight_norm(lin, dim=None)
    assert lin.weight_g.numpy().shape == (1,)
    np.testing.assert_allclose(lin.weight.numpy(), w0, rtol=1e-5)


def test_spectral_norm_unit_spectral_radius():
    lin = nn.Linear(6, 4)
    spectral_norm(lin, n_power_iterations=20)
    x = paddle.to_tensor(np.eye(6, dtype=np.float32))
    lin.train()
    lin(x)  # run power iteration
    sigma = np.linalg.svd(lin.weight.numpy(), compute_uv=False)[0]
    np.testing.assert_allclose(sigma, 1.0, rtol=1e-3)
    names = dict(lin.named_parameters())
    assert "weight_orig" in names and "weight" not in names


def test_parameters_to_vector_roundtrip():
    lin = nn.Linear(3, 2)
    vec = parameters_to_vector(lin.parameters())
    assert vec.numpy().shape == (3 * 2 + 2,)
    new = np.arange(8, dtype=np.float32)
    vector_to_parameters(paddle.to_tensor(new), lin.parameters())
    np.testing.assert_allclose(lin.weight.numpy().reshape(-1), new[:6])
    np.testing.assert_allclose(lin.bias.numpy(), new[6:])
    with pytest.raises(ValueError, match="elements"):
        vector_to_parameters(paddle.to_tensor(new[:5]), lin.parameters())


# ---------------------------------------------------------------------------
# p2p
# ---------------------------------------------------------------------------

def test_batch_isend_irecv_pairs_in_controller():
    import paddle_tpu.distributed as dist

    src = paddle.to_tensor(np.arange(4, dtype=np.float32))
    dst = paddle.to_tensor(np.zeros(4, np.float32))
    ops = [dist.P2POp(dist.isend, src, 1),
           dist.P2POp(dist.irecv, dst, 0)]
    tasks = dist.batch_isend_irecv(ops)
    for t in tasks:
        t.wait()
    np.testing.assert_allclose(dst.numpy(), [0, 1, 2, 3])
    with pytest.raises(RuntimeError, match="matching"):
        dist.batch_isend_irecv([dist.P2POp(dist.irecv, dst, 0)])
    with pytest.raises(ValueError, match="isend/irecv"):
        dist.P2POp(dist.all_reduce, dst, 0)


def test_isend_irecv_over_rpc_world():
    """Self-world p2p through the rpc mailbox (the cross-process path,
    exercised rank->self so one process covers both ends)."""
    import socket
    import paddle_tpu.distributed as dist
    from paddle_tpu.distributed import rpc

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    rpc.init_rpc("w0", rank=0, world_size=1,
                 master_endpoint=f"127.0.0.1:{port}")
    try:
        payload = paddle.to_tensor(np.full((3,), 7.0, np.float32))
        out = paddle.to_tensor(np.zeros(3, np.float32))
        t_send = dist.isend(payload, dst=0)
        t_recv = dist.irecv(out, src=0)
        t_send.wait()
        t_recv.wait()
        np.testing.assert_allclose(out.numpy(), 7.0)
        # ordering: two sends arrive in sequence
        a = paddle.to_tensor(np.array([1.0], np.float32))
        b = paddle.to_tensor(np.array([2.0], np.float32))
        dist.send(a, dst=0)
        dist.send(b, dst=0)
        r1 = paddle.to_tensor(np.zeros(1, np.float32))
        r2 = paddle.to_tensor(np.zeros(1, np.float32))
        dist.recv(r1, src=0)
        dist.recv(r2, src=0)
        assert r1.numpy()[0] == 1.0 and r2.numpy()[0] == 2.0
    finally:
        rpc.shutdown()
