"""Telemetry subsystem tests: metrics registry (concurrency + exposition
round-trip), device memory stats, hot-path instrumentation landing in the
chrome trace, per-rank run telemetry from a real 2-process
``distributed.spawn`` run merged into one summary, and the profiler
satellite fixes (final-step flush, pb export, time units, benchmark
denominators, scheduler edges).

Parity model: the reference has no metrics API to mirror; the profiler
pieces follow reference unittests/test_profiler.py, the registry follows
the Prometheus client data model.
"""
import json
import os
import threading
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer as opt, profiler
from paddle_tpu.observability import (
    MetricsRegistry, TelemetryCallback, get_registry, merge_run_dir,
)
from paddle_tpu.observability.runlog import RunLogger
from paddle_tpu.profiler import Profiler, ProfilerState, make_scheduler
from paddle_tpu.profiler.profiler import aggregate_events, format_agg_table

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_mesh_state():
    """Earlier suites may leave a global mesh (sometimes without an hcg)
    behind; these tests build exactly the mesh they need."""
    from paddle_tpu.distributed import mesh as mesh_mod
    saved = (mesh_mod._global_mesh, mesh_mod._hcg)
    mesh_mod._global_mesh, mesh_mod._hcg = None, None
    yield
    mesh_mod._global_mesh, mesh_mod._hcg = saved


# ===========================================================================
# metrics registry
# ===========================================================================

def test_registry_counter_gauge_histogram_basics():
    reg = MetricsRegistry()
    c = reg.counter("c_total", "a counter")
    c.inc()
    c.inc(2.5, op="all_reduce")
    with pytest.raises(ValueError):
        c.labels().inc(-1)
    g = reg.gauge("g")
    g.set(7)
    g.inc(3, host="w0")
    h = reg.histogram("h", buckets=(0.1, 1.0))
    for v in (0.05, 0.5, 5.0):
        h.observe(v)
    snap = {(r["name"], tuple(sorted(r["labels"].items()))): r
            for r in reg.snapshot()}
    assert snap[("c_total", ())]["value"] == 1.0
    assert snap[("c_total", (("op", "all_reduce"),))]["value"] == 2.5
    assert snap[("g", ())]["value"] == 7.0
    assert snap[("g", (("host", "w0"),))]["value"] == 3.0
    hs = snap[("h", ())]
    assert hs["count"] == 3 and hs["min"] == 0.05 and hs["max"] == 5.0
    assert hs["buckets"] == {"0.1": 1, "1": 2, "+Inf": 3}


def test_registry_type_conflict_and_reset():
    reg = MetricsRegistry()
    reg.counter("m")
    with pytest.raises(TypeError):
        reg.gauge("m")
    reg.reset()
    reg.gauge("m")  # fine after reset


def test_registry_threaded_increments():
    """Concurrent increments from many threads must not lose updates."""
    reg = MetricsRegistry()
    c = reg.counter("hits_total")
    h = reg.histogram("lat", buckets=(0.5,))
    n_threads, per_thread = 8, 500

    def work(i):
        for k in range(per_thread):
            c.inc()
            c.inc(1, worker=str(i))
            h.observe(k % 2)

    threads = [threading.Thread(target=work, args=(i,))
               for i in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value == n_threads * per_thread
    total = sum(r["value"] for r in reg.snapshot()
                if r["name"] == "hits_total" and r["labels"])
    assert total == n_threads * per_thread
    assert h.labels()._state()["count"] == n_threads * per_thread


def test_prometheus_and_jsonl_round_trip(tmp_path):
    reg = MetricsRegistry()
    reg.counter("req_total", "requests").inc(3, code="200", path='a"b')
    reg.gauge("temp").set(36.6)
    reg.histogram("lat_seconds", buckets=(0.1, 1.0)).observe(0.25)

    text = reg.to_prometheus()
    assert '# TYPE req_total counter' in text
    assert 'req_total{code="200",path="a\\"b"} 3' in text
    assert "temp 36.6" in text
    assert 'lat_seconds_bucket{le="0.1"} 0' in text
    assert 'lat_seconds_bucket{le="1"} 1' in text
    assert 'lat_seconds_bucket{le="+Inf"} 1' in text
    assert "lat_seconds_sum 0.25" in text and "lat_seconds_count 1" in text

    path = str(tmp_path / "snap.jsonl")
    reg.export_jsonl(path, extra={"rank": 3})
    recs = [json.loads(l) for l in open(path)]
    assert all(r["rank"] == 3 and "ts" in r for r in recs)
    byname = {r["name"]: r for r in recs}
    assert byname["req_total"]["value"] == 3
    assert byname["lat_seconds"]["count"] == 1
    assert byname["lat_seconds"]["p50"] == 0.25


# ===========================================================================
# device memory stats
# ===========================================================================

def test_device_memory_stats_sees_allocations():
    from paddle_tpu import device
    device.reset_max_memory_allocated()
    base = device.memory_allocated()
    keep = paddle.to_tensor(np.ones((256, 256), np.float32))
    st = device.memory_stats()
    assert st["allocated_bytes"] >= base + 256 * 256 * 4
    assert device.max_memory_allocated() >= st["allocated_bytes"]
    assert st["source"] in ("allocator", "live_arrays")
    del keep


# ===========================================================================
# scheduler edge cases (satellite)
# ===========================================================================

def test_make_scheduler_skip_first_and_repeat_exhaustion():
    sched = make_scheduler(closed=0, ready=0, record=2, repeat=2,
                           skip_first=3)
    states = [sched(i) for i in range(9)]
    assert states[:3] == [ProfilerState.CLOSED] * 3          # skip_first
    assert states[3] == ProfilerState.RECORD
    assert states[4] == ProfilerState.RECORD_AND_RETURN      # cycle 1 end
    assert states[5] == ProfilerState.RECORD
    assert states[6] == ProfilerState.RECORD_AND_RETURN      # cycle 2 end
    assert states[7:] == [ProfilerState.CLOSED] * 2          # exhausted


def test_make_scheduler_ready_span_transitions():
    sched = make_scheduler(closed=2, ready=3, record=1, repeat=0)
    expect = [ProfilerState.CLOSED] * 2 + [ProfilerState.READY] * 3 + \
        [ProfilerState.RECORD_AND_RETURN]
    assert [sched(i) for i in range(6)] == expect
    # repeat=0 cycles forever
    assert [sched(6 + i) for i in range(6)] == expect
    assert sched(600 + 5) == ProfilerState.RECORD_AND_RETURN


# ===========================================================================
# profiler satellites: final-step flush, pb export, time units
# ===========================================================================

def test_profiler_stop_flushes_final_step(capsys):
    p = Profiler(scheduler=(0, 4), targets=[profiler.ProfilerTarget.CPU])
    p.start()
    for _ in range(2):
        time.sleep(0.002)
        p.step()
    time.sleep(0.002)
    p.stop()  # the in-flight third step must be flushed
    assert len(p._step_times) == 3
    assert all(t > 0 for t in p._step_times)


def test_profiler_export_pb_raises(tmp_path):
    p = Profiler(targets=[profiler.ProfilerTarget.CPU])
    with pytest.raises(NotImplementedError):
        p.export(str(tmp_path / "t.pb"), format="pb")


def test_profiler_summary_honors_time_unit(capsys):
    p = Profiler(scheduler=(0, 1), targets=[profiler.ProfilerTarget.CPU])
    p.start()
    with profiler.RecordEvent("op_x"):
        time.sleep(0.005)
    p.step()
    p.stop()
    agg_us = p.summary(time_unit="us")
    out_us = capsys.readouterr().out
    assert "Total(us)" in out_us
    agg_ms = p.summary(time_unit="ms")
    out_ms = capsys.readouterr().out
    assert "Total(ms)" in out_ms
    assert agg_us["op_x"]["total_us"] == pytest.approx(
        agg_ms["op_x"]["total_ms"] * 1e3)
    assert agg_us["op_x"]["total_ms"] == agg_ms["op_x"]["total_ms"]
    with pytest.raises(ValueError):
        p.summary(time_unit="fortnights")


def test_benchmark_separate_denominators():
    """Mixed samples-fed and sample-less step() calls: ips must divide the
    sample count by only the samples-fed steps' elapsed time (satellite)."""
    from paddle_tpu.profiler.timer import _Benchmark
    b = _Benchmark()
    b.begin()
    # 2 sample-less steps of ~8ms, then 2 fed steps of ~2ms each
    for _ in range(2):
        time.sleep(0.008)
        b.step()
    for _ in range(2):
        time.sleep(0.002)
        b.step(num_samples=100)
    r = b.report()
    assert r["samples"] == 200
    assert r["sampled_elapsed_s"] < r["elapsed_s"]
    # correct ips uses the fed-step window only: 200 / ~0.004s >> the
    # wrong 200 / ~0.020s
    assert r["ips"] > 200 / r["elapsed_s"] * 2
    b.reset()
    assert b.report()["ips"] == 0.0


# ===========================================================================
# instrumented train loop -> chrome trace (spans + memory counters)
# ===========================================================================

class _TinyMLP(nn.Layer):
    def __init__(self):
        super().__init__()
        self.l1 = nn.Linear(8, 16)
        self.l2 = nn.Linear(16, 8)

    def forward(self, x):
        return self.l2(paddle.nn.functional.relu(self.l1(x)))


def _mse(model, x, y):
    d = model(x) - y
    return (d * d).mean()


def test_train_loop_trace_has_spans_and_memory_counters(tmp_path):
    """Acceptance: a chrome trace exported from an instrumented train loop
    contains RecordEvent spans from ParallelTrainStep/collectives AND
    memory counter ("ph": "C") events."""
    from paddle_tpu.distributed import all_reduce, mesh as mesh_mod
    from paddle_tpu.distributed.fleet.train_step import ParallelTrainStep
    from paddle_tpu.distributed.mesh import HybridCommunicateGroup

    saved = (mesh_mod._global_mesh, mesh_mod._hcg)
    try:
        mesh_mod._global_mesh, mesh_mod._hcg = None, None
        hcg = HybridCommunicateGroup(dp_degree=2, mp_degree=1)
        model = _TinyMLP()
        step = ParallelTrainStep(
            model, opt.SGD(learning_rate=0.1,
                           parameters=model.parameters()),
            _mse, hcg=hcg)
        rng = np.random.default_rng(0)
        x = paddle.to_tensor(rng.standard_normal((8, 8)).astype(np.float32))
        y = paddle.to_tensor(rng.standard_normal((8, 8)).astype(np.float32))

        p = Profiler(scheduler=(0, 4), targets=[profiler.ProfilerTarget.CPU])
        p.start()
        for _ in range(4):  # first call is compile-labeled, not a step
            step(x, y)
            t = paddle.to_tensor(np.ones((4, 4), np.float32))
            all_reduce(t)
            p.step()
        p.stop()
    finally:
        mesh_mod._global_mesh, mesh_mod._hcg = saved

    path = str(tmp_path / "train.paddle_trace.json")
    p.export(path)
    doc = json.load(open(path))
    spans = {e["name"] for e in doc["traceEvents"] if e["ph"] == "X"}
    counters = {e["name"] for e in doc["traceEvents"] if e["ph"] == "C"}
    assert "ParallelTrainStep.step" in spans
    assert "collective.all_reduce" in spans
    assert "device_memory_bytes" in counters
    cvals = [e["args"]["value"] for e in doc["traceEvents"]
             if e["ph"] == "C" and e["name"] == "device_memory_bytes"]
    assert cvals and all(v > 0 for v in cvals)

    # registry side: step histogram + collective byte counters moved
    snap = get_registry().snapshot()
    names = {r["name"] for r in snap}
    assert "paddle_train_step_seconds" in names
    assert "paddle_collective_bytes_total" in names
    steps = [r for r in snap if r["name"] == "paddle_train_step_seconds"
             and r["labels"].get("path") == "parallel"]
    assert steps and steps[0]["count"] >= 3

    # trace_summary CLI over the same trace (satellite smoke)
    from tools.trace_summary import summarize
    lines = summarize(path, top=5)
    text = "\n".join(lines)
    assert "ParallelTrainStep.step" in text
    assert "counter device_memory_bytes" in text


def test_trace_summary_shares_aggregation_with_profiler():
    agg = aggregate_events([("a", 2e6), ("a", 4e6), ("b", 1e6)])
    assert agg == {"a": (2, 6e6), "b": (1, 1e6)}
    lines = format_agg_table(agg, time_unit="ms", top=1)
    assert len(lines) == 3 and "a" in lines[2]  # header, rule, top row


# ===========================================================================
# run telemetry: per-rank JSONL + merged summary from a 2-proc spawn run
# ===========================================================================

def _telemetry_train_worker(n_steps):
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np  # noqa: F811
    import paddle_tpu as paddle  # noqa: F811
    from paddle_tpu import nn, optimizer as opt  # noqa: F811
    from paddle_tpu.distributed import all_reduce, mesh as mesh_mod
    from paddle_tpu.distributed.fleet.train_step import ParallelTrainStep
    from paddle_tpu.distributed.mesh import HybridCommunicateGroup
    from paddle_tpu.observability.runlog import get_run_logger

    mesh_mod._global_mesh, mesh_mod._hcg = None, None
    hcg = HybridCommunicateGroup(dp_degree=2, mp_degree=1)

    class MLP(nn.Layer):
        def __init__(self):
            super().__init__()
            self.l1 = nn.Linear(8, 8)

        def forward(self, x):
            return self.l1(x)

    model = MLP()
    step = ParallelTrainStep(
        model, opt.SGD(learning_rate=0.1, parameters=model.parameters()),
        lambda m, x, y: (lambda d: (d * d).mean())(m(x) - y), hcg=hcg)
    rng = np.random.default_rng(0)
    x = paddle.to_tensor(rng.standard_normal((8, 8)).astype(np.float32))
    y = paddle.to_tensor(rng.standard_normal((8, 8)).astype(np.float32))
    for _ in range(n_steps):
        step(x, y)
    t = paddle.to_tensor(np.ones((16,), np.float32))
    all_reduce(t)

    logger = get_run_logger()  # from PADDLE_TELEMETRY_DIR (spawn env)
    assert logger is not None, "telemetry dir not inherited by worker"
    logger.log("worker_done", steps=n_steps)
    logger.flush_metrics()


def test_spawn_run_writes_per_rank_telemetry_and_merged_summary(tmp_path):
    """Acceptance: a 2-process distributed.spawn training run writes
    per-rank JSONL telemetry plus a merged run summary containing the
    step-time histogram, collective byte counters, restart count, and
    peak device memory."""
    import paddle_tpu.distributed as dist

    run_dir = str(tmp_path / "run")
    os.environ["PADDLE_TELEMETRY_DIR"] = run_dir
    # workers train independently (own 8-device mesh each); skip the
    # jax.distributed world bootstrap the spawn env contract triggers
    os.environ["_PADDLE_TPU_BOOTSTRAPPED"] = "1"
    try:
        dist.spawn(_telemetry_train_worker, args=(4,), nprocs=2)
    finally:
        os.environ.pop("PADDLE_TELEMETRY_DIR", None)
        os.environ.pop("_PADDLE_TPU_BOOTSTRAPPED", None)

    for rank in (0, 1):
        assert os.path.exists(
            os.path.join(run_dir, f"events.rank{rank}.jsonl"))
        assert os.path.exists(
            os.path.join(run_dir, f"metrics.rank{rank}.gen0.jsonl"))

    summary = merge_run_dir(run_dir)
    assert os.path.exists(os.path.join(run_dir, "run_summary.json"))
    assert summary["ranks"] == [0, 1]
    # 4 calls x 2 ranks, minus each rank's compile-labeled first call
    assert summary["step_time"]["count"] >= 6
    assert summary["step_time"]["max_seconds"] > 0
    per_rank = summary["step_time"]["per_rank"]
    assert {k.split(":")[0] for k in per_rank} == {"0", "1"}
    assert all(k.endswith(":parallel") for k in per_rank), per_rank
    assert summary["collective_bytes"].get("all_reduce", 0) > 0
    assert summary["restarts"] == 0                    # no faults injected
    assert summary["peak_memory_bytes"] > 0
    assert summary["events"].get("worker_done") == 2


def test_merge_run_dir_restart_and_exit_accounting(tmp_path):
    """Controller-side events fold into restart counts and exit codes."""
    run_dir = str(tmp_path)
    # fresh registry: the process-global one may carry real restart
    # counters from other suites' elastic tests into the metrics flush
    with RunLogger(run_dir, rank=-1, generation=0,
                   registry=MetricsRegistry()) as log:
        log.log("launch", generation_launched=0)
        log.log("worker_exit", code=-9, rank_exited=1, generation_exited=0)
        log.log("relaunch", restarts=2)
        log.log("worker_exit", code=0, rank_exited=0, generation_exited=2)
    summary = merge_run_dir(run_dir, write=False)
    assert summary["restarts"] == 2
    assert summary["exit_codes"] == {"-9": 1, "0": 1}
    assert summary["events"]["relaunch"] == 1


# ===========================================================================
# hapi TelemetryCallback
# ===========================================================================

def test_hapi_fit_with_telemetry_callback(tmp_path):
    from paddle_tpu.hapi import Model
    from paddle_tpu.io import TensorDataset

    rng = np.random.default_rng(0)
    xs = rng.standard_normal((16, 8)).astype(np.float32)
    ys = rng.standard_normal((16, 8)).astype(np.float32)
    model = Model(_TinyMLP())
    model.prepare(optimizer=opt.SGD(learning_rate=0.01,
                                    parameters=model.parameters()),
                  loss=lambda out, y: (lambda d: (d * d).mean())(out - y))
    run_dir = str(tmp_path / "fit_run")
    cb = TelemetryCallback(run_dir=run_dir)
    model.fit(TensorDataset([paddle.to_tensor(xs), paddle.to_tensor(ys)]),
              batch_size=4, epochs=2, verbose=0, callbacks=[cb])

    # benchmark timer was reset + fed by the callback
    rep = profiler.benchmark().report()
    assert rep["steps"] >= 4 and rep["ips"] > 0
    # fit-path step series landed in the registry
    fit_steps = [r for r in get_registry().snapshot()
                 if r["name"] == "paddle_train_step_seconds"
                 and r["labels"].get("path") == "fit"]
    assert fit_steps and fit_steps[0]["count"] >= 8
    # run dir has events + metrics for this rank
    events = [json.loads(l) for l in
              open(os.path.join(run_dir, "events.rank0.jsonl"))]
    kinds = [e["event"] for e in events]
    assert "fit_begin" in kinds and "fit_end" in kinds
    assert kinds.count("epoch_end") == 2
    assert os.path.exists(os.path.join(run_dir, "metrics.rank0.gen0.jsonl"))
