"""Op-surface audit + OpTest-style numeric cases for the round-4 op batch
(VERDICT r3 #7: audit vs phi/api/yaml + implement the top missing ops).

Oracle style mirrors the reference's OpTest: hand-computed or
numpy/jax-reference expected values per op.
"""
import os
import sys

import numpy as np
import pytest
import jax
import jax.numpy as jnp

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
import paddle_tpu.vision.ops as vops

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
YAML_DIR = "/root/reference/paddle/phi/api/yaml"


@pytest.mark.skipif(not os.path.isdir(YAML_DIR), reason="no reference yaml")
def test_audit_zero_missing():
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import op_audit
    results = op_audit.audit(YAML_DIR)
    for fname, rows in results.items():
        missing = [op for op, st in rows if st == "MISSING"]
        assert not missing, f"{fname}: {missing}"


def test_lu_unpack_reconstructs():
    rng = np.random.default_rng(0)
    a = rng.standard_normal((4, 4)).astype(np.float32)
    lu_packed, piv = paddle.linalg.lu(paddle.to_tensor(a))
    P, L, U = paddle.linalg.lu_unpack(lu_packed, piv)
    rec = P.numpy() @ L.numpy() @ U.numpy()
    np.testing.assert_allclose(rec, a, rtol=1e-4, atol=1e-5)


def test_inverse_alias():
    a = np.array([[2.0, 0.0], [1.0, 3.0]], np.float32)
    inv = paddle.inverse(paddle.to_tensor(a))
    np.testing.assert_allclose(inv.numpy() @ a, np.eye(2), atol=1e-5)


def test_clip_by_norm():
    x = paddle.to_tensor(np.array([3.0, 4.0], np.float32))
    out = paddle.nn.clip_by_norm(x, 1.0)
    np.testing.assert_allclose(out.numpy(), [0.6, 0.8], rtol=1e-5)
    out2 = paddle.nn.clip_by_norm(x, 10.0)  # under the cap: unchanged
    np.testing.assert_allclose(out2.numpy(), [3.0, 4.0], rtol=1e-6)


def test_fill_diagonal_and_tensor():
    x = paddle.to_tensor(np.zeros((3, 3), np.float32))
    x.fill_diagonal_(5.0)
    np.testing.assert_allclose(x.numpy(), np.eye(3) * 5)

    # wrap=True matches numpy's fill_diagonal on tall matrices
    t = paddle.to_tensor(np.zeros((7, 3), np.float32))
    t.fill_diagonal_(1.0, wrap=True)
    ref = np.zeros((7, 3), np.float32)
    np.fill_diagonal(ref, 1.0, wrap=True)
    np.testing.assert_allclose(t.numpy(), ref)

    y = paddle.to_tensor(np.zeros((3, 3), np.float32))
    d = paddle.to_tensor(np.array([1.0, 2.0, 3.0], np.float32))
    out = paddle.fill_diagonal_tensor(y, d)
    np.testing.assert_allclose(out.numpy(), np.diag([1.0, 2.0, 3.0]))


def test_inplace_random_fills():
    paddle.seed(7)
    x = paddle.to_tensor(np.zeros((1000,), np.float32))
    x.uniform_(2.0, 3.0)
    assert 2.0 <= float(x.numpy().min()) and float(x.numpy().max()) <= 3.0
    y = paddle.to_tensor(np.zeros((4000,), np.float32))
    y.exponential_(lam=2.0)
    assert (y.numpy() >= 0).all()
    assert abs(float(y.numpy().mean()) - 0.5) < 0.06  # E = 1/lam


def test_huber_loss():
    x = paddle.to_tensor(np.array([0.0, 2.0], np.float32))
    t = paddle.to_tensor(np.array([0.5, 0.0], np.float32))
    out = F.huber_loss(x, t, delta=1.0, reduction="none")
    np.testing.assert_allclose(out.numpy(), [0.125, 1.5], rtol=1e-6)


def test_edit_distance():
    a = paddle.to_tensor(np.array([[1, 2, 3, 0]], np.int64))
    b = paddle.to_tensor(np.array([[1, 3, 3, 4]], np.int64))
    la = paddle.to_tensor(np.array([3], np.int64))
    lb = paddle.to_tensor(np.array([4], np.int64))
    d, n = paddle.edit_distance(a, b, normalized=False,
                                input_length=la, label_length=lb)
    # "123" -> "1334": sub(2->3) + ins(4) = 2
    np.testing.assert_allclose(d.numpy(), [[2.0]])
    assert int(n.numpy()[0]) == 1


def test_send_uv():
    import paddle_tpu.geometric as geo
    x = paddle.to_tensor(np.array([[1.0], [2.0], [3.0]], np.float32))
    y = paddle.to_tensor(np.array([[10.0], [20.0], [30.0]], np.float32))
    src = paddle.to_tensor(np.array([0, 2], np.int64))
    dst = paddle.to_tensor(np.array([1, 0], np.int64))
    out = geo.send_uv(x, y, src, dst, message_op="add")
    np.testing.assert_allclose(out.numpy(), [[21.0], [13.0]])


def test_prior_box():
    feat = paddle.to_tensor(np.zeros((1, 8, 2, 2), np.float32))
    img = paddle.to_tensor(np.zeros((1, 3, 32, 32), np.float32))
    boxes, variances = vops.prior_box(
        feat, img, min_sizes=[16.0], aspect_ratios=[1.0], clip=True)
    assert boxes.shape == [2, 2, 1, 4]
    b = boxes.numpy()[0, 0, 0]  # center (8, 8), size 16 -> [0, 0, .5, .5]
    np.testing.assert_allclose(b, [0.0, 0.0, 0.5, 0.5], atol=1e-6)
    np.testing.assert_allclose(variances.numpy()[0, 0, 0],
                               [0.1, 0.1, 0.2, 0.2])


def test_multiclass_and_matrix_nms():
    bb = np.array([[[0, 0, 10, 10], [0, 0, 10.5, 10], [20, 20, 30, 30]]],
                  np.float32)
    sc = np.zeros((1, 2, 3), np.float32)
    sc[0, 1] = [0.9, 0.8, 0.7]  # class 1; class 0 = background
    out, nums = vops.multiclass_nms(
        paddle.to_tensor(bb), paddle.to_tensor(sc),
        score_threshold=0.1, nms_threshold=0.5, background_label=0)
    # overlapping pair suppressed -> 2 detections
    assert int(nums.numpy()[0]) == 2
    assert out.numpy()[0][1] == pytest.approx(0.9)

    out_m, nums_m = vops.matrix_nms(
        paddle.to_tensor(bb), paddle.to_tensor(sc),
        score_threshold=0.1, post_threshold=0.5, background_label=0)
    got = out_m.numpy()
    # decay kills the overlapping 0.8 box below post_threshold
    assert int(nums_m.numpy()[0]) == 2 and got.shape[1] == 6


def test_psroi_pool():
    # C = out_c * ph * pw, output-channel-major: channel for output c,
    # bin (i, j) is c*ph*pw + i*pw + j (R-FCN convention)
    x = np.zeros((1, 8, 4, 4), np.float32)
    for c in range(8):
        x[0, c] = c + 1
    boxes = paddle.to_tensor(np.array([[0.0, 0.0, 4.0, 4.0]], np.float32))
    num = paddle.to_tensor(np.array([1], np.int32))
    out = vops.psroi_pool(paddle.to_tensor(x), boxes, num, 2)
    np.testing.assert_allclose(out.numpy()[0, 0], [[1.0, 2.0], [3.0, 4.0]])
    np.testing.assert_allclose(out.numpy()[0, 1], [[5.0, 6.0], [7.0, 8.0]])


def test_distribute_fpn_proposals():
    rois = np.array([[0, 0, 10, 10], [0, 0, 300, 300]], np.float32)
    multi, restore, nums = vops.distribute_fpn_proposals(
        paddle.to_tensor(rois), 2, 5, 4, 224,
        rois_num=paddle.to_tensor(np.array([1, 1], np.int32)))
    sizes = [m.numpy().shape[0] for m in multi]
    assert sizes == [1, 0, 1, 0]  # small->level2, 300px->level4
    r = restore.numpy()[:, 0]
    assert sorted(r.tolist()) == [0, 1]
    # per-IMAGE counts, shape [N] per level
    assert nums[0].numpy().tolist() == [1, 0]
    assert nums[2].numpy().tolist() == [0, 1]


def test_generate_proposals():
    H = W = 4
    A = 1
    scores = np.random.default_rng(3).uniform(0, 1, (1, A, H, W)) \
        .astype(np.float32)
    deltas = np.zeros((1, 4 * A, H, W), np.float32)
    anchors = np.zeros((H, W, A, 4), np.float32)
    for i in range(H):
        for j in range(W):
            anchors[i, j, 0] = [j * 8, i * 8, j * 8 + 16, i * 8 + 16]
    var = np.ones_like(anchors)
    rois, rscores, num = vops.generate_proposals(
        paddle.to_tensor(scores), paddle.to_tensor(deltas),
        paddle.to_tensor(np.array([[32, 32]], np.float32)),
        paddle.to_tensor(anchors), paddle.to_tensor(var),
        pre_nms_top_n=16, post_nms_top_n=4, nms_thresh=0.5)
    assert rois.numpy().shape[1] == 4
    assert int(num.numpy()[0]) == rois.numpy().shape[0] <= 4
    # scores sorted descending
    s = rscores.numpy()[:, 0]
    assert (np.diff(s) <= 1e-6).all()


def test_deform_conv2d_zero_offset_equals_conv():
    """With zero offsets and no mask, deformable conv == plain conv."""
    rng = np.random.default_rng(5)
    x = rng.standard_normal((1, 3, 8, 8)).astype(np.float32)
    w = rng.standard_normal((4, 3, 3, 3)).astype(np.float32) * 0.2
    off = np.zeros((1, 2 * 9, 6, 6), np.float32)
    out = vops.deform_conv2d(paddle.to_tensor(x), paddle.to_tensor(off),
                             paddle.to_tensor(w), stride=1, padding=0)
    ref = F.conv2d(paddle.to_tensor(x), paddle.to_tensor(w),
                   stride=1, padding=0)
    np.testing.assert_allclose(out.numpy(), ref.numpy(), rtol=1e-4,
                               atol=1e-4)
    # offsets get gradients
    offt = paddle.to_tensor(off)
    offt.stop_gradient = False
    out2 = vops.deform_conv2d(paddle.to_tensor(x), offt,
                              paddle.to_tensor(w))
    out2.sum().backward()
    assert offt.grad is not None


def test_yolo_loss_behavior():
    """Perfect logits -> small loss; perturbed -> larger. Finite grads."""
    N, A, cls, H, W = 1, 3, 2, 4, 4
    anchors = [10, 13, 16, 30, 33, 23]
    gt = np.zeros((N, 2, 4), np.float32)
    gt[0, 0] = [0.4, 0.4, 0.2, 0.2]   # one box; second is padding
    gl = np.zeros((N, 2), np.int64)
    x = np.zeros((N, A * (5 + cls), H, W), np.float32)
    x[:, :] = -6.0  # low objectness everywhere

    t = paddle.to_tensor(x)
    t.stop_gradient = False
    loss = vops.yolo_loss(t, paddle.to_tensor(gt), paddle.to_tensor(gl),
                          anchors, [0, 1, 2], cls, ignore_thresh=0.7,
                          downsample_ratio=8)
    assert loss.shape == [N]
    v = float(loss.numpy()[0])
    assert np.isfinite(v) and v > 0
    loss.sum().backward()
    g = t.grad.numpy()
    assert np.isfinite(g).all() and np.abs(g).max() > 0


def test_sequence_ops_roundtrip_and_pool():
    flat = paddle.to_tensor(
        np.arange(10, dtype=np.float32).reshape(5, 2))
    lens = paddle.to_tensor(np.array([2, 3], np.int64))
    padded, out_lens = paddle.sequence_pad(flat, 0.0, lens)
    assert list(padded.shape) == [2, 3, 2]
    np.testing.assert_allclose(padded.numpy()[0, 2], [0, 0])  # padding
    back = paddle.sequence_unpad(padded, out_lens)
    np.testing.assert_allclose(back.numpy(), flat.numpy())

    pooled = paddle.sequence_pool(padded, "average", lens)
    np.testing.assert_allclose(pooled.numpy()[0], flat.numpy()[:2].mean(0))
    np.testing.assert_allclose(pooled.numpy()[1], flat.numpy()[2:].mean(0))
    last = paddle.sequence_last_step(padded, lens)
    np.testing.assert_allclose(last.numpy()[1], flat.numpy()[4])

    sm = paddle.sequence_softmax(padded[:, :, 0], lens)
    s = sm.numpy()
    np.testing.assert_allclose(s.sum(1), [1.0, 1.0], rtol=1e-5)
    assert s[0, 2] == 0.0  # masked slot

    rev = paddle.sequence_reverse(padded, lens)
    np.testing.assert_allclose(rev.numpy()[0, 0], flat.numpy()[1])
    np.testing.assert_allclose(rev.numpy()[0, 2], padded.numpy()[0, 2])


def test_sequence_expand_concat_slice_enumerate_erase():
    x = paddle.to_tensor(np.array([[1.0], [2.0]], np.float32))
    rep = paddle.to_tensor(np.array([2, 3], np.int64))
    ex = paddle.sequence_expand(x, rep)
    np.testing.assert_allclose(ex.numpy()[:, 0], [1, 1, 2, 2, 2])

    a = paddle.to_tensor(np.array([[1.0, 2.0, 0.0]], np.float32))
    b = paddle.to_tensor(np.array([[5.0, 0.0, 0.0]], np.float32))
    la = paddle.to_tensor(np.array([2], np.int64))
    lb = paddle.to_tensor(np.array([1], np.int64))
    cat, lc = paddle.sequence_concat([a, b], [la, lb])
    np.testing.assert_allclose(cat.numpy()[0], [1.0, 2.0, 5.0])
    assert int(lc.numpy()[0]) == 3

    sl, ls = paddle.sequence_slice(
        cat, paddle.to_tensor(np.array([1], np.int64)),
        paddle.to_tensor(np.array([2], np.int64)))
    np.testing.assert_allclose(sl.numpy()[0], [2.0, 5.0])

    en = paddle.sequence_enumerate(
        paddle.to_tensor(np.array([[1, 2, 3]], np.int64)), 2, pad_value=0)
    np.testing.assert_allclose(en.numpy()[0], [[1, 2], [2, 3], [3, 0]])

    er, le = paddle.sequence_erase(
        paddle.to_tensor(np.array([[1, 2, 1, 3]], np.int64)), [1])
    np.testing.assert_allclose(er.numpy()[0], [2, 3, 0, 0])
    assert int(le.numpy()[0]) == 2


def test_auc_functional():
    p = paddle.to_tensor(np.array([0.1, 0.4, 0.35, 0.8], np.float32))
    y = paddle.to_tensor(np.array([0, 0, 1, 1], np.int64))
    val, sp, sn = paddle.metric.auc(p, y)
    # sklearn roc_auc_score for this case = 0.75
    assert abs(float(val.numpy()) - 0.75) < 0.01


def test_decode_jpeg_roundtrip(tmp_path):
    from PIL import Image
    import io as _io
    img = np.random.default_rng(0).integers(0, 255, (8, 8, 3)) \
        .astype(np.uint8)
    buf = _io.BytesIO()
    Image.fromarray(img).save(buf, format="JPEG", quality=95)
    data = np.frombuffer(buf.getvalue(), np.uint8)
    out = vops.decode_jpeg(paddle.to_tensor(data))
    assert list(out.shape)[0] == 3 and out.shape[1] == 8
