"""OpTest-style numeric oracle sweep.

Parity: the reference's single most important fixture
(``unittests/op_test.py:327 OpTest`` — SURVEY §4.1): each op is checked
against a numpy oracle for values, and against finite differences for
gradients, across the op surface in one parametrized table.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import ops


def _np(t):
    return np.asarray(t._value)


RNG = np.random.default_rng(0)
A = RNG.standard_normal((4, 5)).astype(np.float32)
B_ = RNG.standard_normal((4, 5)).astype(np.float32)
POS = np.abs(A) + 0.5
INTS = RNG.integers(0, 9, (4, 5)).astype(np.int64)

# (name, paddle_fn, numpy_fn, inputs)
UNARY = [
    ("exp", ops.exp, np.exp, A),
    ("log", ops.log, np.log, POS),
    ("log2", ops.log2, np.log2, POS),
    ("log10", ops.log10, np.log10, POS),
    ("log1p", ops.log1p, np.log1p, POS),
    ("sqrt", ops.sqrt, np.sqrt, POS),
    ("rsqrt", ops.rsqrt, lambda x: 1 / np.sqrt(x), POS),
    ("abs", ops.abs, np.abs, A),
    ("sin", ops.sin, np.sin, A),
    ("cos", ops.cos, np.cos, A),
    ("tan", ops.tan, np.tan, A * 0.3),
    ("asin", ops.asin, np.arcsin, A * 0.3),
    ("acos", ops.acos, np.arccos, A * 0.3),
    ("atan", ops.atan, np.arctan, A),
    ("sinh", ops.sinh, np.sinh, A),
    ("cosh", ops.cosh, np.cosh, A),
    ("tanh", ops.tanh, np.tanh, A),
    ("floor", ops.floor, np.floor, A * 3),
    ("ceil", ops.ceil, np.ceil, A * 3),
    ("round", ops.round, np.round, A * 3),
    ("sign", ops.sign, np.sign, A),
    ("reciprocal", ops.reciprocal, lambda x: 1 / x, POS),
    ("square", ops.square, np.square, A),
    ("erf", ops.erf, None, A),  # scipy-free: check via known values below
    ("expm1", ops.expm1, np.expm1, A),
]

BINARY = [
    ("add", ops.add, np.add),
    ("subtract", ops.subtract, np.subtract),
    ("multiply", ops.multiply, np.multiply),
    ("divide", ops.divide, np.divide),
    ("maximum", ops.maximum, np.maximum),
    ("minimum", ops.minimum, np.minimum),
    ("pow", lambda x, y: ops.pow(x, 2.0), lambda x, y: x ** 2.0),
    ("atan2", ops.atan2, np.arctan2),
    ("fmax", ops.fmax, np.fmax),
    ("fmin", ops.fmin, np.fmin),
]

REDUCTIONS = [
    ("sum", ops.sum, np.sum),
    ("mean", ops.mean, np.mean),
    ("max", ops.max, np.max),
    ("min", ops.min, np.min),
    ("prod", ops.prod, np.prod),
]


@pytest.mark.parametrize("name,pfn,nfn,x", UNARY,
                         ids=[u[0] for u in UNARY])
def test_unary_matches_numpy(name, pfn, nfn, x):
    got = _np(pfn(paddle.to_tensor(x)))
    if nfn is None:
        assert np.isfinite(got).all()
        return
    np.testing.assert_allclose(got, nfn(x), rtol=2e-5, atol=1e-6)


def test_erf_known_values():
    x = np.array([0.0, 1.0, -1.0, 2.0], np.float32)
    got = _np(ops.erf(paddle.to_tensor(x)))
    want = np.array([0.0, 0.8427008, -0.8427008, 0.9953223], np.float32)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("name,pfn,nfn", BINARY,
                         ids=[b[0] for b in BINARY])
def test_binary_matches_numpy(name, pfn, nfn):
    got = _np(pfn(paddle.to_tensor(A), paddle.to_tensor(POS)))
    np.testing.assert_allclose(got, nfn(A, POS), rtol=2e-5, atol=1e-6)


@pytest.mark.parametrize("name,pfn,nfn", REDUCTIONS,
                         ids=[r[0] for r in REDUCTIONS])
@pytest.mark.parametrize("axis", [None, 0, 1])
def test_reduction_matches_numpy(name, pfn, nfn, axis):
    got = _np(pfn(paddle.to_tensor(A), axis=axis))
    np.testing.assert_allclose(got, nfn(A, axis=axis), rtol=2e-5, atol=2e-6)


_GRAD_CASES = [u for u in UNARY if u[0] in
               ("exp", "log", "sqrt", "tanh", "sin", "square", "abs")]


@pytest.mark.parametrize("name,pfn,nfn,x", _GRAD_CASES,
                         ids=[u[0] for u in _GRAD_CASES])
def test_unary_grad_matches_finite_difference(name, pfn, nfn, x):
    """check_grad parity (op_test.py:2122): analytic vs central difference."""
    t = paddle.to_tensor(x.astype(np.float64))
    t.stop_gradient = False
    ops.sum(pfn(t)).backward()
    analytic = _np(t.grad)
    eps = 1e-6
    num = (nfn(x.astype(np.float64) + eps)
           - nfn(x.astype(np.float64) - eps)) / (2 * eps)
    np.testing.assert_allclose(analytic, num, rtol=1e-4, atol=1e-6,
                               err_msg=name)


def test_manipulation_ops():
    x = paddle.to_tensor(A)
    np.testing.assert_allclose(_np(ops.transpose(x, [1, 0])), A.T)
    np.testing.assert_allclose(_np(ops.reshape(x, [5, 4])),
                               A.reshape(5, 4))
    np.testing.assert_allclose(_np(ops.flip(x, axis=0)), A[::-1])
    np.testing.assert_allclose(_np(ops.roll(x, 2, axis=1)),
                               np.roll(A, 2, 1))
    np.testing.assert_allclose(
        _np(ops.concat([x, x], axis=0)), np.concatenate([A, A], 0))
    np.testing.assert_allclose(_np(ops.stack([x, x], axis=0)),
                               np.stack([A, A]))
    parts = ops.split(x, 5, axis=1)
    assert len(parts) == 5
    np.testing.assert_allclose(_np(parts[2]), A[:, 2:3])
    np.testing.assert_allclose(_np(ops.tile(x, [2, 1])), np.tile(A, (2, 1)))
    np.testing.assert_allclose(_np(ops.squeeze(ops.unsqueeze(x, 0), 0)), A)


def test_search_sort_ops():
    x = paddle.to_tensor(A)
    np.testing.assert_allclose(_np(ops.argmax(x, axis=1)),
                               A.argmax(1))
    np.testing.assert_allclose(_np(ops.argmin(x, axis=0)), A.argmin(0))
    np.testing.assert_allclose(_np(ops.sort(x, axis=1)), np.sort(A, 1))
    np.testing.assert_allclose(_np(ops.argsort(x, axis=1)),
                               np.argsort(A, 1))
    vals, idx = ops.topk(x, k=2, axis=1)
    np.testing.assert_allclose(_np(vals), -np.sort(-A, 1)[:, :2])
    w = ops.where(paddle.to_tensor(A > 0), paddle.to_tensor(A),
                  paddle.to_tensor(B_))
    np.testing.assert_allclose(_np(w), np.where(A > 0, A, B_))


def test_cumulative_and_logic():
    x = paddle.to_tensor(A)
    np.testing.assert_allclose(_np(ops.cumsum(x, axis=1)),
                               np.cumsum(A, 1), rtol=1e-6)
    np.testing.assert_allclose(_np(ops.cumprod(x, dim=1)),
                               np.cumprod(A, 1), rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(
        _np(ops.logical_and(paddle.to_tensor(A > 0),
                            paddle.to_tensor(B_ > 0))),
        (A > 0) & (B_ > 0))
    np.testing.assert_allclose(_np(ops.isnan(paddle.to_tensor(A / POS))),
                               np.isnan(A / POS))
    np.testing.assert_allclose(
        _np(ops.clip(x, -0.5, 0.5)), np.clip(A, -0.5, 0.5))


def test_int_ops():
    x = paddle.to_tensor(INTS)
    np.testing.assert_allclose(_np(ops.mod(x, 4)), INTS % 4)
    np.testing.assert_allclose(
        _np(ops.floor_divide(x, paddle.to_tensor(np.int64(3)))), INTS // 3)
    np.testing.assert_allclose(_np(ops.bitwise_and(x, x)), INTS)


def test_linalg_against_numpy():
    m = RNG.standard_normal((4, 4)).astype(np.float64)
    m = m @ m.T + 4 * np.eye(4)  # SPD
    t = paddle.to_tensor(m)
    np.testing.assert_allclose(_np(ops.det(t)), np.linalg.det(m), rtol=1e-8)
    np.testing.assert_allclose(_np(ops.inv(t)), np.linalg.inv(m), rtol=1e-8)
    np.testing.assert_allclose(_np(ops.cholesky(t)), np.linalg.cholesky(m),
                               rtol=1e-8)
    evals = np.sort(_np(ops.eigvalsh(t)))
    np.testing.assert_allclose(evals, np.sort(np.linalg.eigvalsh(m)),
                               rtol=1e-8)
    b = RNG.standard_normal((4, 2))
    np.testing.assert_allclose(_np(ops.solve(t, paddle.to_tensor(b))),
                               np.linalg.solve(m, b), rtol=1e-8)
