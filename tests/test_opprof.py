"""Op-level profile↔prediction attribution + self-calibrating cost model.

Covers the opprof tentpole end to end on CPU: the eqn-by-eqn replay
harness (rows + `unattributed` residual sum EXACTLY to the measured
step total), the site-tagging pass under jit, PTCM001 drift findings +
the drift gauge, calibration fitting (post-fit mean |rel_err| of the
predicted step time <= pre-fit, by construction), the
PADDLE_COST_CALIBRATION / PADDLE_CHIP_KIND consumption paths, the
checked-in ``tests/fixtures/opprof_run`` doctor gate (``--ops``), and
the attribution-aware tools (trace_summary, bench_compare refusal).
"""
import json
import os
import shutil
import sys
import time

import pytest

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax
import jax.numpy as jnp

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

FIXTURE = os.path.join(REPO, "tests", "fixtures", "opprof_run")

from paddle_tpu.observability import opprof
from paddle_tpu.observability.calibration import (
    apply_to_chip, calibration_id, fit_calibration, load_calibration,
    save_calibration,
)
from paddle_tpu.observability.instrument import chip_specs


def _toy_fn(x, w):
    h = jnp.tanh(x @ w)
    return (h * h).sum()


def _toy_args(n=64, k=32):
    return (jnp.ones((n, 2 * n), jnp.float32),
            jnp.ones((2 * n, k), jnp.float32))


# ---------------------------------------------------------------------------
# replay harness + the join
# ---------------------------------------------------------------------------

def test_replay_attribution_sums_exactly_to_total():
    attr = opprof.replay_attribution(_toy_fn, _toy_args())
    row_sum, total = attr.sum_check()
    # float addition of the very numbers in the table — exact, not approx
    assert row_sum == pytest.approx(total, abs=1e-9)
    assert total > 0
    resid = [r for r in attr.rows if r["family"] == opprof.UNATTRIBUTED]
    assert len(resid) == 1
    # wall total >= sum of per-eqn windows by construction
    assert resid[0]["measured_ms"] >= -1e-9
    fams = {r["family"] for r in attr.rows}
    assert "dot" in fams and "elementwise" in fams


def test_replay_sites_stable_across_runs():
    a1 = opprof.replay_attribution(_toy_fn, _toy_args())
    a2 = opprof.replay_attribution(_toy_fn, _toy_args())
    sites = lambda a: {r["site"] for r in a.rows}
    assert sites(a1) == sites(a2)
    # predictions are static — identical across replays
    p = lambda a: {r["site"]: r["predicted_ms"] for r in a.rows}
    assert p(a1) == p(a2)


def test_replay_joins_predictions_and_rel_err():
    attr = opprof.replay_attribution(_toy_fn, _toy_args(),
                                     chip=chip_specs("v5e"))
    assert attr.chip == "v5e"
    dot = [r for r in attr.rows if r["family"] == "dot"]
    assert dot and dot[0]["predicted_ms"] > 0 and dot[0]["flops"] > 0
    for r in attr.rows:
        if r["family"] == opprof.UNATTRIBUTED:
            assert r["rel_err"] is None
        elif r["predicted_ms"] > 0:
            assert r["rel_err"] == pytest.approx(
                (r["measured_ms"] - r["predicted_ms"]) / r["predicted_ms"])


def test_replay_applies_family_corrections():
    spec = chip_specs("v5e")
    base = opprof.replay_attribution(_toy_fn, _toy_args(), chip=spec,
                                     calibration={})
    cal = {"family_correction": {"dot": 2.0}, "calibration_id": "x" * 12}
    corr = opprof.replay_attribution(_toy_fn, _toy_args(), chip=spec,
                                     calibration=cal)
    assert corr.calibration_id == "x" * 12
    p = lambda a: {r["site"]: r["predicted_ms"] for r in a.rows
                   if r["family"] == "dot"}
    for site, val in p(corr).items():
        assert val == pytest.approx(2.0 * p(base)[site])


def test_tag_sites_traces_and_matches_eager():
    args = _toy_args()
    closed = jax.make_jaxpr(_toy_fn)(*args)
    tagged = jax.jit(opprof.tag_sites(closed))
    assert float(tagged(*args)) == pytest.approx(float(_toy_fn(*args)))


def test_attribution_roundtrip_and_views(tmp_path):
    attr = opprof.replay_attribution(_toy_fn, _toy_args())
    path = attr.save(str(tmp_path / "attribution.json"))
    back = opprof.OpAttribution.load(path)
    assert back.sum_check() == attr.sum_check()
    assert back.by_family().keys() == attr.by_family().keys()
    top = back.top_deviations(2)
    assert len(top) == 2
    assert all(r["family"] != opprof.UNATTRIBUTED for r in top)


def test_attach_glue_cost_ranks_candidates():
    attr = opprof.OpAttribution(rows=[
        {"site": "a.py:L1:cumsum", "family": "scatter_gather",
         "measured_ms": 3.0},
        {"site": "a.py:L2:gather", "family": "scatter_gather",
         "measured_ms": 1.0},
    ], measured_total_ms=4.0)
    cands = [{"glue_bytes": 1.0, "sites": ["a.py:L2:gather"]},
             {"glue_bytes": 2.0,
              "sites": ["a.py:L1:cumsum", "a.py:L2:gather"]},
             {"glue_bytes": 3.0, "sites": ["missing.py:L9:sort"]}]
    out = opprof.attach_glue_cost(cands, attr)
    assert out[0]["measured_glue_ms"] == pytest.approx(4.0)
    assert out[1]["measured_glue_ms"] == pytest.approx(1.0)
    assert "measured_glue_ms" not in out[2]


def test_ingest_profiler_trace_chrome_spans(tmp_path):
    closed = jax.make_jaxpr(_toy_fn)(*_toy_args())
    from paddle_tpu.analysis.passes.cost import (estimate_jaxpr_cost,
                                                 site_rows)
    rows = site_rows(estimate_jaxpr_cost(closed, chip=chip_specs("v5e")))
    scope = opprof._scope_name(rows[0]["site"])
    trace = {"traceEvents": [
        {"ph": "X", "name": f"jit__fn/{scope}/fusion.1", "ts": 0.0,
         "dur": 700.0},
        {"ph": "X", "name": "jit__fn/unrelated.2", "ts": 700.0,
         "dur": 300.0},
    ]}
    path = tmp_path / "trace.json"
    path.write_text(json.dumps(trace))
    attr = opprof.ingest_profiler_trace(str(path), rows, chip="v5e")
    assert attr.source == "jax_profiler"
    row_sum, total = attr.sum_check()
    assert row_sum == pytest.approx(total, abs=1e-9)
    assert total == pytest.approx(1.0)  # wall extent of the trace, ms
    hit = [r for r in attr.rows if r["site"] == rows[0]["site"]]
    assert hit[0]["measured_ms"] == pytest.approx(0.7)
    resid = [r for r in attr.rows
             if r["family"] == opprof.UNATTRIBUTED][0]
    assert resid["measured_ms"] == pytest.approx(0.3)


# ---------------------------------------------------------------------------
# PTCM001 drift
# ---------------------------------------------------------------------------

def _drifted_attr():
    return {
        "schema": "op_attribution", "measured_total_ms": 10.0,
        "rows": [
            {"site": "a.py:L1:dot_general", "family": "dot",
             "measured_ms": 5.0, "predicted_ms": 5.2},
            {"site": "a.py:L2:cumsum", "family": "scatter_gather",
             "measured_ms": 4.0, "predicted_ms": 0.5},
            {"site": "unattributed", "family": "unattributed",
             "measured_ms": 1.0, "predicted_ms": 0.0},
        ],
    }


def test_drift_findings_and_gauge():
    findings = opprof.drift_findings(_drifted_attr(), publish=True)
    assert [f["code"] for f in findings] == ["PTCM001"]
    f = findings[0]
    assert f["severity"] == "warning" and f["family"] == "scatter_gather"
    assert f["ratio"] == pytest.approx(8.0)
    from paddle_tpu.observability.metrics import get_registry
    g = get_registry().get("paddle_cost_model_drift_ratio")
    vals = {labels["family"]: state["value"] for labels, state
            in g.collect()}
    # every finite-ratio family lands on the gauge, drifted or not
    assert vals["scatter_gather"] == pytest.approx(8.0)
    assert vals["dot"] == pytest.approx(5.0 / 5.2, rel=1e-3)


def test_drift_min_ms_suppresses_noise():
    attr = _drifted_attr()
    attr["rows"][1]["measured_ms"] = 0.01   # below DRIFT_MIN_MS
    attr["rows"][1]["predicted_ms"] = 0.001
    assert opprof.drift_findings(attr, publish=False) == []


# ---------------------------------------------------------------------------
# calibration
# ---------------------------------------------------------------------------

def test_fit_family_corrections_recovers_known_ratio():
    rows = [{"family": "dot", "measured_ms": 2.0 * p, "predicted_ms": p}
            for p in (1.0, 2.0, 5.0)]
    cal = fit_calibration(rows=rows, chip="cpu")
    assert cal["family_correction"]["dot"] == pytest.approx(2.0)
    fit = cal["fit"]["families"]["dot"]
    assert fit["post"] <= fit["pre"]
    # pathological traces clamp instead of baking in a broken model
    rows = [{"family": "other", "measured_ms": 100.0,
             "predicted_ms": 1.0}]
    cal = fit_calibration(rows=rows, chip="cpu")
    assert cal["family_correction"]["other"] == pytest.approx(10.0)


def test_calibration_id_stable_and_content_addressed(tmp_path):
    cal = fit_calibration(rows=[{"family": "dot", "measured_ms": 2.0,
                                 "predicted_ms": 1.0}], chip="v5e")
    assert cal["calibration_id"] == calibration_id(cal)
    path = save_calibration(cal, str(tmp_path / "calibration.json"))
    back = load_calibration(path)
    assert back["calibration_id"] == cal["calibration_id"]
    # content change => id change (stale hand-edited ids are re-stamped)
    doc = json.load(open(path))
    doc["mxu_efficiency"] = 0.123
    json.dump(doc, open(path, "w"))
    assert load_calibration(path)["calibration_id"] \
        != cal["calibration_id"]


def _step_sweep():
    """Tiny sweep: measured jit wall time next to the cost model's
    roofline components for a few small programs of different bounds."""
    progs = []
    for n in (96, 160):
        progs.append((lambda x, w: x @ w, _toy_args(n)))
        progs.append((_toy_fn, _toy_args(n)))
    spec = chip_specs("cpu")
    from paddle_tpu.analysis.passes.cost import estimate_jaxpr_cost
    pairs, closeds = [], []
    for fn, args in progs:
        closed = jax.make_jaxpr(fn)(*args)
        cost = estimate_jaxpr_cost(closed, chip=spec)
        jfn = jax.jit(fn)
        jax.block_until_ready(jfn(*args))       # compile outside timing
        reps = 5
        t0 = time.perf_counter()
        for _ in range(reps):
            out = jfn(*args)
        jax.block_until_ready(out)
        measured_ms = (time.perf_counter() - t0) / reps * 1e3
        pairs.append({"measured_ms": measured_ms,
                      "compute_ms": cost.compute_ms,
                      "hbm_ms": cost.hbm_ms, "comm_ms": cost.comm_ms})
        closeds.append((closed, measured_ms))
    return spec, pairs, closeds


def test_calibration_improves_step_prediction():
    spec, pairs, closeds = _step_sweep()
    cal = fit_calibration(step_pairs=pairs, chip="cpu")
    fit = cal["fit"]["step"]
    # identity is always a candidate: post <= pre on the fit set, hard
    assert fit["post"] <= fit["pre"]
    # and on this hardware the hand constants are wrong enough that the
    # fit strictly improves (unless the model was already within 2%)
    assert fit["post"] < fit["pre"] or fit["pre"] <= 0.02

    # end to end: re-pricing the sweep through chip_specs-style consumption
    # (apply_to_chip) reduces the mean |rel_err| of predicted step_ms
    from paddle_tpu.analysis.passes.cost import estimate_jaxpr_cost
    cal_spec = apply_to_chip(spec, cal)
    assert cal_spec["calibration_id"] == cal["calibration_id"]

    def mean_err(chip):
        errs = [abs(estimate_jaxpr_cost(c, chip=chip).step_ms - m) / m
                for c, m in closeds]
        return sum(errs) / len(errs)
    assert mean_err(cal_spec) <= mean_err(spec) + 1e-9


def test_calibration_env_consumed_by_chip_specs(tmp_path, monkeypatch):
    cal = {"chip": "v5e", "mxu_efficiency": 0.3, "hbm_bw_fraction": 0.5,
           "family_correction": {}}
    path = save_calibration(cal, str(tmp_path / "calibration.json"))
    monkeypatch.setenv("PADDLE_COST_CALIBRATION", path)
    s = chip_specs("v5e")
    assert s["mxu_efficiency"] == pytest.approx(0.3)
    assert s["hbm_bw"] == pytest.approx(819e9 * 0.5)
    assert s["calibration_id"] == load_calibration(path)["calibration_id"]
    from paddle_tpu.observability.calibration import active_calibration_id
    assert active_calibration_id() == s["calibration_id"]
    # a v5e calibration never silently prices another part
    v4 = chip_specs("v4")
    assert "calibration_id" not in v4 and "mxu_efficiency" not in v4
    # and estimate_jaxpr_cost picks the constants up through the spec
    from paddle_tpu.analysis.passes.cost import estimate_jaxpr_cost
    closed = jax.make_jaxpr(_toy_fn)(*_toy_args())
    calibrated = estimate_jaxpr_cost(closed, chip=s).step_ms
    monkeypatch.delenv("PADDLE_COST_CALIBRATION")
    default = estimate_jaxpr_cost(closed, chip=chip_specs("v5e")).step_ms
    assert calibrated != default


def test_default_calibration_id_without_env(monkeypatch):
    monkeypatch.delenv("PADDLE_COST_CALIBRATION", raising=False)
    from paddle_tpu.observability.calibration import active_calibration_id
    assert active_calibration_id() == "default"


# ---------------------------------------------------------------------------
# chip_specs satellites
# ---------------------------------------------------------------------------

def test_chip_kind_env_override(monkeypatch):
    monkeypatch.setenv("PADDLE_CHIP_KIND", "v6e")
    assert chip_specs()["name"] == "v6e"
    # an explicit argument still wins over the env
    assert chip_specs("v5p")["name"] == "v5p"


def test_cpu_specs_are_microbenched_not_fantasy(monkeypatch):
    # conftest pins the cpu row for suite determinism — clear the cache
    # here, where the live microbench is the thing under test
    from paddle_tpu.observability import instrument
    monkeypatch.setattr(instrument, "_cpu_bench_cache", None)
    s = chip_specs("cpu")
    # the old placeholder row said exactly 1e12 / 50e9; the microbench
    # replaces both with measured-but-clamped host numbers
    assert 1e10 <= s["peak_flops"] <= 5e13
    assert 1e9 <= s["hbm_bw"] <= 2e11
    assert 1.0 <= s["hbm_gb"] <= 64.0
    assert s["ici_bw"] == 10e9          # no interconnect to measure
    # cached: a second call reuses the measurement
    assert chip_specs("cpu")["peak_flops"] == s["peak_flops"]


# ---------------------------------------------------------------------------
# fixture doctor gate + tools
# ---------------------------------------------------------------------------

def test_perf_doctor_opprof_fixture_gate(capsys):
    from tools.perf_doctor import main as doctor_main
    assert doctor_main([FIXTURE, "--ops", "--no-write"]) == 0
    out = capsys.readouterr().out
    assert "rows sum 400.0000ms = measured total 400.0000ms" in out
    assert "PTCM001" in out and "scatter_gather" in out
    assert "measured glue" in out
    assert not os.path.exists(os.path.join(FIXTURE, "run_summary.json"))


def test_perf_doctor_opprof_fixture_json(tmp_path, capsys):
    from tools.perf_doctor import main as doctor_main
    run_dir = str(tmp_path / "run")
    shutil.copytree(FIXTURE, run_dir)
    assert doctor_main([run_dir, "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    attr = doc["op_attribution"]
    assert sum(r["measured_ms"] for r in attr["rows"]) \
        == attr["measured_total_ms"]
    kinds = {f["kind"] for f in doc["findings"]}
    assert "cost_model_drift" in kinds
    assert "fusion_glue_measured" in kinds
    glue = attr["fusion_candidates"][0]
    assert glue["measured_glue_ms"] == 90.0 and len(glue["sites"]) == 2


def test_doctor_flags_sum_contract_violation():
    from paddle_tpu.observability.doctor import collect_findings
    attr = _drifted_attr()
    attr["rows"][0]["measured_ms"] += 0.5    # break the contract
    findings = collect_findings({}, op_attribution=attr)
    assert "attribution_sum_mismatch" in {f["kind"] for f in findings}


def test_decode_subfamilies_scale_to_decode_bucket():
    from paddle_tpu.observability.doctor import decode_subfamilies
    sattr = {"buckets": {"decode": 2.0, "queue": 0.1}}
    # measured attribution wins
    sub = decode_subfamilies(sattr, op_attribution=_drifted_attr())
    assert sum(sub.values()) == pytest.approx(2.0, abs=1e-6)
    assert sub["scatter_gather"] == pytest.approx(2.0 * 4.0 / 9.0,
                                                  abs=1e-3)
    # predicted family split is the fallback
    sub = decode_subfamilies(
        sattr, serving_predicted={
            "predicted_decode_family_ms": {"dot": 3.0, "elementwise": 1.0}})
    assert sub["dot"] == pytest.approx(1.5)
    assert sum(sub.values()) == pytest.approx(2.0, abs=1e-6)


def test_serving_predicted_row_carries_family_split():
    from paddle_tpu.serving.predict import predicted_serving_row
    row = predicted_serving_row("tiny", concurrency=2, page_size=8)
    fam = row["predicted_decode_family_ms"]
    assert fam and "dot" in fam
    assert all(v >= 0 for v in fam.values())
    assert row["calibration_id"] == "default"


def test_trace_summary_ops_and_diff(capsys):
    from tools.trace_summary import main as ts_main
    attr_path = os.path.join(FIXTURE, "attribution.json")
    assert ts_main([attr_path, "--ops"]) == 0
    out = capsys.readouterr().out
    assert "rows sum 400.0000ms" in out
    # attribution files ride the existing chrome-trace diff plumbing
    assert ts_main(["--diff", attr_path, attr_path, "--top", "3"]) == 0
    out = capsys.readouterr().out
    assert "net span-time delta" in out and "+0.000ms" in out
    # plain summarize treats rows as spans
    assert ts_main([attr_path, "--top", "2"]) == 0
    assert "train.py:L42:dot_general" in capsys.readouterr().out


def test_bench_compare_refuses_cross_calibration_anchor():
    from tools.bench_compare import compare
    meas = {"metric": "gpt_345m_tokens_per_sec_per_chip",
            "value": 30000.0, "unit": "tokens/s/chip",
            "extras": {"calibration_id": "default"}}
    pred = {"metric": "gpt_345m_predicted", "value": 40000.0,
            "unit": "tokens/s/chip (static cost model)",
            "extras": {"calibration_id": "default"}}
    pred_refit = dict(pred, extras={"calibration_id": "deadbeef0123"})
    rows = lambda p: {"gpt_345m_tokens_per_sec_per_chip": meas,
                      "gpt_345m_predicted": p}
    ok = compare(rows(pred), rows(pred))
    rec = [m for m in ok["metrics"]
           if m["metric"] == "gpt_345m_tokens_per_sec_per_chip"][0]
    assert rec["anchored_ratio_a"] == pytest.approx(0.75)
    refused = compare(rows(pred), rows(pred_refit))
    rec = [m for m in refused["metrics"]
           if m["metric"] == "gpt_345m_tokens_per_sec_per_chip"][0]
    assert "anchored_ratio_a" not in rec
    assert "calibration mismatch" in rec["anchor_refused"]
    # rows that predate the stamp compare as "default" (back-compat)
    from tools.bench_compare import _calibration_of
    assert _calibration_of({"extras": {}}) == "default"


def test_bench_rows_stamp_calibration_id(monkeypatch):
    import bench
    monkeypatch.setattr(bench, "_CAL_ID", None)
    monkeypatch.delenv("PADDLE_COST_CALIBRATION", raising=False)
    printed = []
    monkeypatch.setattr("builtins.print",
                        lambda *a, **k: printed.append(a[0]))
    bench.emit("toy_metric", 1.0, "unit", {"x": 1})
    row = json.loads(printed[0])
    assert row["extras"]["calibration_id"] == "default"
    assert row["extras"]["x"] == 1


def test_analysis_predicted_row_carries_calibration_id(monkeypatch):
    monkeypatch.delenv("PADDLE_COST_CALIBRATION", raising=False)
    from paddle_tpu.analysis.predict import predicted_row
    from paddle_tpu.distributed import mesh as mesh_mod
    from paddle_tpu.distributed.mesh import HybridCommunicateGroup
    from paddle_tpu.models.gpt import GPTHybridTrainStep, gpt_tiny_config
    mesh_mod._global_mesh, mesh_mod._hcg = None, None
    hcg = HybridCommunicateGroup(dp_degree=1, mp_degree=1, pp_degree=1)
    step = GPTHybridTrainStep.abstract(gpt_tiny_config(), hcg, n_micro=1,
                                       remat=False,
                                       compute_dtype="float32")
    row = predicted_row(step, 2, 64, chip="v5e")
    assert row["calibration_id"] == "default"


def test_profiler_pb_export_points_at_attribution(tmp_path):
    from paddle_tpu.profiler.profiler import Profiler
    with pytest.raises(NotImplementedError) as ei:
        Profiler().export(str(tmp_path / "x.pb"), format="pb")
    msg = str(ei.value)
    assert "opprof" in msg and "attribution" in msg
