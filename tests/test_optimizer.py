"""Optimizer + LR scheduler tests (numeric oracles vs hand-rolled numpy)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as opt


def _quadratic_steps(optimizer_fn, n=50):
    """Minimize ||w - 3||^2; return final w."""
    w = paddle.Parameter(np.zeros((4,), "float32"))
    o = optimizer_fn([w])
    for _ in range(n):
        loss = ((w - 3.0) ** 2).sum()
        loss.backward()
        o.step()
        o.clear_grad()
    return w.numpy()


def test_sgd_converges():
    w = _quadratic_steps(lambda p: opt.SGD(0.1, parameters=p), 100)
    np.testing.assert_allclose(w, np.full(4, 3.0), rtol=1e-3)


def test_momentum_converges():
    w = _quadratic_steps(lambda p: opt.Momentum(0.05, 0.9, parameters=p), 100)
    np.testing.assert_allclose(w, np.full(4, 3.0), rtol=1e-2)


def test_adam_converges():
    w = _quadratic_steps(lambda p: opt.Adam(0.3, parameters=p), 120)
    np.testing.assert_allclose(w, np.full(4, 3.0), rtol=1e-2)


def test_adam_matches_reference_formula():
    np.random.seed(1)
    w0 = np.random.rand(3).astype("float32")
    g = np.random.rand(3).astype("float32")
    p = paddle.Parameter(w0.copy())
    o = opt.Adam(learning_rate=0.1, parameters=[p])
    p.grad = paddle.to_tensor(g)
    o.step()
    # manual adam step 1
    m = 0.1 * g
    v = 0.001 * g * g
    mhat = m / (1 - 0.9)
    vhat = v / (1 - 0.999)
    ref = w0 - 0.1 * mhat / (np.sqrt(vhat) + 1e-8)
    np.testing.assert_allclose(p.numpy(), ref, rtol=1e-5)


def test_adamw_decoupled_decay():
    w0 = np.full(2, 10.0, "float32")
    p = paddle.Parameter(w0.copy())
    o = opt.AdamW(learning_rate=0.1, weight_decay=0.5, parameters=[p])
    p.grad = paddle.to_tensor(np.zeros(2, "float32"))
    o.step()
    # zero grad -> update is pure decay: w *= (1 - lr*wd)
    np.testing.assert_allclose(p.numpy(), w0 * (1 - 0.1 * 0.5), rtol=1e-5)


def test_lamb_steps():
    w = _quadratic_steps(lambda p: opt.Lamb(0.05, parameters=p), 100)
    assert abs(w.mean() - 3.0) < 1.0  # lamb normalizes; just check direction


def test_optimizer_state_roundtrip():
    p = paddle.Parameter(np.ones(3, "float32"))
    o = opt.Adam(0.1, parameters=[p])
    p.grad = paddle.to_tensor(np.ones(3, "float32"))
    o.step()
    st = o.state_dict()
    p2 = paddle.Parameter(np.ones(3, "float32"))
    o2 = opt.Adam(0.1, parameters=[p2])
    p2.grad = paddle.to_tensor(np.ones(3, "float32"))
    o2.step()  # allocate accumulators
    o2.set_state_dict(st)
    assert o2._global_step == 1


def test_weight_decay_l2():
    p = paddle.Parameter(np.full(2, 2.0, "float32"))
    o = opt.SGD(0.1, parameters=[p], weight_decay=opt.L2Decay(0.5))
    p.grad = paddle.to_tensor(np.zeros(2, "float32"))
    o.step()
    # g_eff = 0 + 0.5*2 = 1; w = 2 - 0.1*1
    np.testing.assert_allclose(p.numpy(), np.full(2, 1.9), rtol=1e-6)


def test_grad_clip_in_optimizer():
    p = paddle.Parameter(np.zeros(2, "float32"))
    o = opt.SGD(1.0, parameters=[p],
                grad_clip=nn.ClipGradByGlobalNorm(1.0))
    p.grad = paddle.to_tensor(np.full(2, 100.0, "float32"))
    o.step()
    np.testing.assert_allclose(np.linalg.norm(p.numpy()), 1.0, rtol=1e-4)


def test_lr_schedulers():
    s = opt.lr.StepDecay(0.1, step_size=2, gamma=0.5)
    lrs = [s()]
    for _ in range(4):
        s.step()
        lrs.append(s())
    np.testing.assert_allclose(lrs, [0.1, 0.1, 0.05, 0.05, 0.025], rtol=1e-6)

    w = opt.lr.LinearWarmup(0.1, warmup_steps=5, start_lr=0.0, end_lr=0.1)
    vals = [w()]
    for _ in range(5):
        w.step()
        vals.append(w())
    np.testing.assert_allclose(vals[0], 0.0)
    np.testing.assert_allclose(vals[5], 0.1)

    c = opt.lr.CosineAnnealingDecay(1.0, T_max=10)
    c.last_epoch = 10
    np.testing.assert_allclose(c.get_lr(), 0.0, atol=1e-7)

    m = opt.lr.MultiplicativeDecay(1.0, lambda e: 0.5)
    mv = [m()]
    for _ in range(3):
        m.step()
        mv.append(m())
    np.testing.assert_allclose(mv, [1.0, 0.5, 0.25, 0.125], rtol=1e-6)


def test_scheduler_drives_optimizer():
    sched = opt.lr.StepDecay(0.5, step_size=1, gamma=0.1)
    p = paddle.Parameter(np.zeros(1, "float32"))
    o = opt.SGD(sched, parameters=[p])
    assert o.get_lr() == 0.5
    sched.step()
    assert abs(o.get_lr() - 0.05) < 1e-9


def test_amp_autocast_and_scaler():
    m = nn.Linear(4, 4)
    x = paddle.to_tensor(np.random.rand(2, 4).astype("float32"))
    with paddle.amp.auto_cast(dtype="bfloat16"):
        y = m(x)
    assert y.dtype == paddle.bfloat16
    # black-listed op forced back to f32
    with paddle.amp.auto_cast(dtype="bfloat16"):
        z = paddle.exp(y)
    assert z.dtype == paddle.float32

    scaler = paddle.amp.GradScaler(init_loss_scaling=8.0)
    o = opt.SGD(0.1, parameters=m.parameters())
    loss = m(x).sum()
    scaler.scale(loss).backward()
    scaler.step(o)
    scaler.update()
    assert not scaler._found_inf


def test_scaler_skips_on_inf():
    p = paddle.Parameter(np.ones(2, "float32"))
    o = opt.SGD(0.1, parameters=[p])
    scaler = paddle.amp.GradScaler(init_loss_scaling=4.0,
                                   decr_every_n_nan_or_inf=1)
    p.grad = paddle.to_tensor(np.array([np.inf, 1.0], "float32"))
    before = p.numpy().copy()
    scaler.step(o)
    scaler.update()
    np.testing.assert_allclose(p.numpy(), before)  # step skipped
    assert scaler._scale == 2.0  # halved


def test_optimizer_restore_matches_uninterrupted():
    """Checkpoint-restore into a FRESH optimizer must continue the exact Adam
    trajectory (accumulators restored lazily on first step)."""
    p = paddle.Parameter(np.ones(3, "float32"))
    o = opt.Adam(0.1, parameters=[p])
    p.grad = paddle.to_tensor(np.ones(3, "float32"))
    o.step()
    sd = o.state_dict()

    p2 = paddle.Parameter(p.numpy())
    o2 = opt.Adam(0.1, parameters=[p2])
    o2.set_state_dict(sd)
    p2.grad = paddle.to_tensor(np.ones(3, "float32"))
    o2.step()

    p3 = paddle.Parameter(np.ones(3, "float32"))
    o3 = opt.Adam(0.1, parameters=[p3])
    for _ in range(2):
        p3.grad = paddle.to_tensor(np.ones(3, "float32"))
        o3.step()
    np.testing.assert_allclose(p2.numpy(), p3.numpy(), rtol=1e-6)
