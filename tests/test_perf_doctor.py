"""Perf-doctor stack tests: flight recorder (ring, dumps, crash paths),
online anomaly detectors, merge_run_dir straggler pass + torn-JSONL
tolerance, predicted-vs-measured gap attribution, the perf_doctor CLI
over the checked-in fixture run dir, and the bench_compare /
trace_summary --diff satellites.

The kill-path acceptance tests run a REAL subprocess (SIGTERM and
unhandled-exception paths) and assert the flight dump it leaves behind —
that is the user-facing contract: a dead run always has a black box.
"""
import json
import os
import shutil
import signal
import subprocess
import sys
import tempfile
import time

import numpy as np
import pytest

from paddle_tpu.observability import anomaly, doctor, flight
from paddle_tpu.observability import instrument as obs
from paddle_tpu.observability import runlog
from paddle_tpu.observability.runlog import merge_run_dir

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURE = os.path.join(REPO, "tests", "fixtures", "perf_doctor_run")


@pytest.fixture(autouse=True)
def _fresh_observability_state(tmp_path, monkeypatch):
    """Isolate the process-global recorder/monitors/run-logger per test;
    the default run dir points into tmp so stray dumps never land in the
    repo (or the checked-in fixture)."""
    monkeypatch.setenv("PADDLE_TELEMETRY_DIR", str(tmp_path / "auto_run"))
    monkeypatch.setattr(runlog, "_run_logger", None)
    flight.reset_for_tests()
    anomaly.reset_monitors()
    yield
    logger = runlog._run_logger
    if logger is not None:
        logger.close()
    monkeypatch.setattr(runlog, "_run_logger", None)
    flight.reset_for_tests()
    anomaly.reset_monitors()


def _counter_value(name, **labels):
    from paddle_tpu.observability import get_registry
    inst = get_registry().get(name)
    if inst is None:
        return 0.0
    total = 0.0
    for lab, state in inst.collect():
        if all(lab.get(k) == v for k, v in labels.items()):
            total += state["value"]
    return total


# ===========================================================================
# detectors
# ===========================================================================

def test_robust_z_flags_spike_not_noise():
    det = anomaly.RollingRobustZ(window=32, z_thresh=6.0, min_samples=8)
    rng = np.random.default_rng(0)
    for v in 0.1 + 0.002 * rng.standard_normal(40):
        assert det.observe(float(v)) is None
    z = det.observe(0.5)
    assert z is not None and z > 6.0


def test_robust_z_anomalies_do_not_poison_the_window():
    det = anomaly.RollingRobustZ(window=32, z_thresh=6.0, min_samples=8)
    for _ in range(16):
        det.observe(0.1)
    # a burst of spikes: every one must flag (the window never absorbs
    # them, so the threshold cannot drift up under attack)
    for _ in range(10):
        assert det.observe(1.0) is not None
    assert det.observe(0.1) is None  # baseline still intact


def test_drift_detector_directions():
    up = anomaly.DriftDetector(baseline_n=8, recent_n=8, rel_thresh=0.2,
                               direction="up")
    for _ in range(8):
        assert up.observe(100.0) is None      # baseline freeze
    for _ in range(7):
        assert up.observe(130.0) is None      # recent window filling
    assert up.observe(130.0) == pytest.approx(0.3)
    down = anomaly.DriftDetector(baseline_n=8, recent_n=8, rel_thresh=0.2,
                                 direction="down")
    for _ in range(8):
        down.observe(0.5)
    got = [down.observe(0.3) for _ in range(8)]
    assert got[-1] == pytest.approx(-0.4)


def test_monitor_step_spike_and_cooldown():
    mon = anomaly.StepAnomalyMonitor("t", window=32, z_thresh=6.0,
                                     cooldown=8, dump_on_anomaly=False)
    for _ in range(20):
        assert mon.observe(0.1) == []
    fired = mon.observe(1.0)
    assert [f["kind"] for f in fired] == ["step_time_spike"]
    assert mon.observe(1.0) == []           # inside cooldown
    for _ in range(8):
        mon.observe(0.1)
    assert [f["kind"] for f in mon.observe(1.0)] == ["step_time_spike"]


def test_monitor_loss_nan_resolves_with_one_step_lag():
    mon = anomaly.StepAnomalyMonitor("t", dump_on_anomaly=False)
    assert mon.observe(0.1, loss=float("nan")) == []   # stored, not read
    fired = mon.observe(0.1, loss=2.0)                 # resolved now
    assert [f["kind"] for f in fired] == ["loss_nan"]


def test_monitor_loss_nan_flush_catches_final_step():
    mon = anomaly.StepAnomalyMonitor("t", dump_on_anomaly=False)
    mon.observe(0.1, loss=float("inf"))
    assert [f["kind"] for f in mon.flush()] == ["loss_nan"]


def test_monitor_loss_spike():
    mon = anomaly.StepAnomalyMonitor("t", window=32, z_thresh=6.0,
                                     dump_on_anomaly=False)
    for _ in range(20):
        mon.observe(0.1, loss=2.0)
    mon.observe(0.1, loss=80.0)
    fired = mon.observe(0.1, loss=2.0)      # spike resolves one step late
    assert [f["kind"] for f in fired] == ["loss_spike"]


def test_monitor_loss_scale_thrash_on_overflow_burst():
    mon = anomaly.StepAnomalyMonitor("t", dump_on_anomaly=False)
    # isolated overflows (healthy dynamic scaling) never fire
    fired = []
    for i in range(40):
        fired += mon.observe(0.1, found_inf=(i % 20 == 0))
    assert fired == []
    # a burst does
    for _ in range(4):
        fired += mon.observe(0.1, found_inf=True)
    assert [f["kind"] for f in fired] == ["loss_scale_thrash"]
    assert fired[0]["value"] >= 4


def test_monitor_memory_creep_and_mfu_drift():
    mon = anomaly.StepAnomalyMonitor("t", dump_on_anomaly=False)
    fired = []
    for i in range(40):
        fired += mon.observe(0.1, mfu=0.5, memory_bytes=1e9)
    assert fired == []
    for i in range(40):
        fired += mon.observe(0.1, mfu=0.3, memory_bytes=1.6e9)
    kinds = {f["kind"] for f in fired}
    assert kinds == {"memory_creep", "mfu_drift"}


def test_monitor_emits_runlog_event_counter_and_flight_dump(tmp_path,
                                                            monkeypatch):
    run_dir = str(tmp_path / "run")
    monkeypatch.setenv("PADDLE_TELEMETRY_DIR", run_dir)
    monkeypatch.setattr(runlog, "_run_logger", None)
    base = _counter_value("paddle_anomalies_total", kind="step_time_spike",
                          path="wired")
    mon = anomaly.StepAnomalyMonitor("wired", window=32, z_thresh=6.0,
                                     dump_on_anomaly=True)
    for _ in range(20):
        mon.observe(0.1)
    assert mon.observe(2.0)
    if mon.last_dump_thread is not None:  # dump runs off the hot path
        mon.last_dump_thread.join(timeout=30)
    assert _counter_value("paddle_anomalies_total", kind="step_time_spike",
                          path="wired") == base + 1
    events, bad = runlog._read_jsonl(
        os.path.join(run_dir, "events.rank0.jsonl"))
    assert bad == 0
    anomalies = [e for e in events if e["event"] == "anomaly"]
    assert anomalies and anomalies[0]["kind"] == "step_time_spike"
    dumps = [p for p in os.listdir(run_dir) if p.startswith("flight.rank")]
    assert dumps, "anomaly firing must leave a flight dump"


# ===========================================================================
# flight recorder
# ===========================================================================

def test_flight_ring_is_bounded_and_keeps_the_tail(tmp_path):
    rec = flight.FlightRecorder(capacity=16, run_dir=str(tmp_path))
    for i in range(50):
        rec.record_step(0.01, loss=float(i), path="t")
    path = rec.dump("final")
    doc = json.load(open(path))
    assert doc["n_records"] == 16
    steps = [r["step"] for r in doc["records"]]
    assert steps == list(range(35, 51))      # the LAST N records
    assert doc["records"][-1]["loss"] == 49.0


def test_flight_dump_resolves_device_scalars(tmp_path):
    import jax.numpy as jnp
    rec = flight.FlightRecorder(run_dir=str(tmp_path))
    rec.record_step(0.01, loss=jnp.asarray(3.5), path="t")
    doc = json.load(open(rec.dump("final")))
    assert doc["records"][0]["loss"] == 3.5


def test_flight_dump_without_a_dir_is_a_noop(monkeypatch):
    monkeypatch.delenv("PADDLE_TELEMETRY_DIR", raising=False)
    monkeypatch.setattr(runlog, "_run_logger", None)
    rec = flight.FlightRecorder()
    rec.record_step(0.01)
    assert rec.dump("exception") is None


def test_flight_soft_dumps_throttle_hard_dumps_do_not(tmp_path):
    rec = flight.FlightRecorder(run_dir=str(tmp_path))
    rec.record_step(0.01)
    assert rec.dump("anomaly") is not None
    assert rec.dump("anomaly") is None       # throttled
    assert rec.dump("exception") is not None  # hard reason: always
    assert rec.dump("preemption") is not None


def test_flight_dump_reentrant_under_held_lock(tmp_path):
    """SIGTERM handlers run on the main thread and can interrupt
    record()/record_step() inside the recorder's critical section;
    dump() must still complete (the lock is reentrant), or the whole
    preemption grace window deadlocks."""
    rec = flight.FlightRecorder(run_dir=str(tmp_path))
    rec.record_step(0.01, step=1)
    assert rec._lock.acquire(blocking=False)
    try:
        # a non-reentrant lock would refuse the same-thread re-acquire
        assert rec._lock.acquire(blocking=False), \
            "recorder lock must be reentrant for the signal-handler dump"
        rec._lock.release()
        path = rec.dump("preemption")
    finally:
        rec._lock.release()
    assert path and json.load(open(path))["n_records"] == 1


def test_preemption_handler_dumps_flight_in_process(tmp_path, monkeypatch):
    from paddle_tpu.distributed.checkpoint import preemption as pre
    run_dir = str(tmp_path / "run")
    monkeypatch.setenv("PADDLE_TELEMETRY_DIR", run_dir)
    monkeypatch.setattr(runlog, "_run_logger", None)
    flight.reset_for_tests()
    rec = flight.get_flight_recorder()
    for i in range(5):
        rec.record_step(0.02, loss=1.0, path="t")

    exit_codes = []
    monkeypatch.setattr(pre, "_exit", exit_codes.append)

    class Mgr:
        saved = None

        def emergency_save(self, state, step, partitions=None):
            Mgr.saved = (state, step)

    handler = pre.PreemptionHandler(Mgr(), lambda: ({"w": 1}, 7))
    handler._handle(signal.SIGTERM, None)
    assert exit_codes == [pre.EMERGENCY_EXIT_CODE]
    assert Mgr.saved == ({"w": 1}, 7)
    dump = os.path.join(run_dir, "flight.rank0.preemption.json")
    assert os.path.exists(dump)
    assert json.load(open(dump))["n_records"] == 5


# --------------------------------------------------------------------------
# kill-path acceptance: a dying PROCESS leaves the black box
# --------------------------------------------------------------------------

_CRASH_SCRIPT = """
import sys, time
sys.path.insert(0, {repo!r})
from paddle_tpu.observability import flight
rec = flight.get_flight_recorder()      # installs the excepthook chain
for i in range(20):
    rec.record_step(0.01, loss=2.0 + 0.1 * i, path="t")
{tail}
"""


def _run_crash_script(tail, run_dir, wait_sigterm=False):
    script = _CRASH_SCRIPT.format(repo=REPO, tail=tail)
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PADDLE_TELEMETRY_DIR=run_dir)
    p = subprocess.Popen([sys.executable, "-c", script],
                         stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                         text=True, env=env)
    if wait_sigterm:
        # wait for the child's READY marker, then deliver the SIGTERM
        assert p.stdout.readline().strip() == "READY"
        p.send_signal(signal.SIGTERM)
    out, err = p.communicate(timeout=60)
    return p.returncode, out, err


def test_unhandled_exception_leaves_flight_dump(tmp_path):
    """Acceptance: a process dying on an unhandled exception leaves a
    flight dump with the last N step records."""
    run_dir = str(tmp_path / "run")
    rc, _, err = _run_crash_script('raise ValueError("boom")', run_dir)
    assert rc == 1 and "boom" in err
    doc = json.load(open(
        os.path.join(run_dir, "flight.rank0.exception.json")))
    assert doc["reason"] == "exception"
    assert "boom" in doc["exception"]
    assert "ValueError" in doc["traceback"]
    steps = [r for r in doc["records"] if r["kind"] == "step"]
    assert len(steps) == 20
    assert steps[-1]["loss"] == pytest.approx(3.9)


def test_sigterm_preemption_leaves_flight_dump_and_exit_75(tmp_path):
    """Acceptance: SIGTERM mid-run → the preemption handler's grace
    window dumps the flight ring, then exits 75 after the emergency
    save contract."""
    run_dir = str(tmp_path / "run")
    tail = """
from paddle_tpu.distributed.checkpoint.preemption import (
    install_preemption_handler)

class Mgr:
    def emergency_save(self, state, step, partitions=None):
        pass

install_preemption_handler(Mgr(), lambda: ({"w": 1}, 7))
print("READY", flush=True)
time.sleep(60)
"""
    rc, _, _ = _run_crash_script(tail, run_dir, wait_sigterm=True)
    assert rc == 75
    doc = json.load(open(
        os.path.join(run_dir, "flight.rank0.preemption.json")))
    assert doc["reason"] == "preemption"
    assert len([r for r in doc["records"] if r["kind"] == "step"]) == 20
    events, _ = runlog._read_jsonl(
        os.path.join(run_dir, "events.rank0.jsonl"))
    kinds = [e["event"] for e in events]
    assert "preemption_signal" in kinds and "preemption_saved" in kinds


# ===========================================================================
# merge_run_dir: torn lines, straggler pass
# ===========================================================================

def _write_rank_metrics(run_dir, rank, mean, count=100, path="parallel",
                        gen=0, extra_recs=()):
    os.makedirs(run_dir, exist_ok=True)
    recs = [{"name": "paddle_train_step_seconds", "type": "histogram",
             "labels": {"path": path}, "count": count, "sum": mean * count,
             "min": mean * 0.9, "max": mean * 1.3, "mean": mean,
             "p50": mean, "p95": mean * 1.1, "generation": gen}]
    recs.extend(extra_recs)
    with open(os.path.join(run_dir,
                           f"metrics.rank{rank}.gen{gen}.jsonl"), "a") as f:
        for r in recs:
            f.write(json.dumps(r) + "\n")


def test_merge_tolerates_and_counts_torn_jsonl(tmp_path):
    run_dir = str(tmp_path)
    _write_rank_metrics(run_dir, 0, 0.1)
    with open(os.path.join(run_dir, "metrics.rank0.gen0.jsonl"), "a") as f:
        f.write('{"name": "paddle_tokens_per_sec", "val')   # torn tail
    with open(os.path.join(run_dir, "events.rank0.jsonl"), "w") as f:
        f.write(json.dumps({"ts": 1, "rank": 0, "generation": 0,
                            "event": "worker_done"}) + "\n")
        f.write("not json at all\n")
    summary = merge_run_dir(run_dir, write=False)
    assert summary["corrupt_lines"] == 2
    assert summary["step_time"]["count"] == 100   # intact lines kept
    assert summary["events"]["worker_done"] == 1


def test_merge_names_seeded_2x_straggler(tmp_path):
    run_dir = str(tmp_path)
    for rank, mean in [(0, 0.1), (1, 0.11), (2, 0.2), (3, 0.1)]:
        _write_rank_metrics(run_dir, rank, mean)
    summary = merge_run_dir(run_dir, write=True)
    strag = summary["straggler"]
    assert strag and strag["rank"] == 2 and strag["generation"] == 0
    assert strag["skew"] == pytest.approx(2.0, rel=0.05)
    # acceptance: named in run_summary.json too
    on_disk = json.load(open(os.path.join(run_dir, "run_summary.json")))
    assert on_disk["straggler"]["rank"] == 2


def test_merge_no_straggler_when_balanced_or_single_rank(tmp_path):
    run_a = str(tmp_path / "a")
    for rank in range(4):
        _write_rank_metrics(run_a, rank, 0.1)
    assert merge_run_dir(run_a, write=False)["straggler"] is None
    run_b = str(tmp_path / "b")
    _write_rank_metrics(run_b, 0, 0.5)
    assert merge_run_dir(run_b, write=False)["straggler"] is None


def test_merge_folds_mfu_and_anomaly_counters(tmp_path):
    run_dir = str(tmp_path)
    _write_rank_metrics(run_dir, 0, 0.1, extra_recs=[
        {"name": "paddle_train_mfu", "type": "gauge",
         "labels": {"path": "parallel"}, "value": 0.44, "generation": 0},
        {"name": "paddle_anomalies_total", "type": "counter",
         "labels": {"kind": "loss_nan", "path": "parallel"}, "value": 2,
         "generation": 0}])
    with open(os.path.join(run_dir, "events.rank0.jsonl"), "w") as f:
        # the same firings as events: must NOT double count
        for _ in range(2):
            f.write(json.dumps({"ts": 1, "rank": 0, "generation": 0,
                                "event": "anomaly", "kind": "loss_nan"})
                    + "\n")
    summary = merge_run_dir(run_dir, write=False)
    assert summary["mfu"] == {"0:g0:parallel": 0.44}
    assert summary["anomalies"] == {"loss_nan": 2}


def test_merge_anomaly_events_fallback_without_counters(tmp_path):
    run_dir = str(tmp_path)
    os.makedirs(run_dir, exist_ok=True)
    with open(os.path.join(run_dir, "events.rank1.jsonl"), "w") as f:
        f.write(json.dumps({"ts": 1, "rank": 1, "generation": 0,
                            "event": "anomaly", "kind": "memory_creep"})
                + "\n")
    summary = merge_run_dir(run_dir, write=False)
    assert summary["anomalies"] == {"memory_creep": 1}


def test_merge_anomalies_include_rank_crashed_before_first_flush(tmp_path):
    """A rank whose firings exist only in its events stream (it died
    before any metrics flush) still contributes, even when OTHER ranks
    flushed anomaly counters — and counter+event for the same rank never
    double count."""
    run_dir = str(tmp_path)
    _write_rank_metrics(run_dir, 0, 0.1, extra_recs=[
        {"name": "paddle_anomalies_total", "type": "counter",
         "labels": {"kind": "step_time_spike", "path": "parallel"},
         "value": 3, "generation": 0}])
    with open(os.path.join(run_dir, "events.rank0.jsonl"), "w") as f:
        for _ in range(3):
            f.write(json.dumps({"ts": 1, "rank": 0, "generation": 0,
                                "event": "anomaly",
                                "kind": "step_time_spike"}) + "\n")
    with open(os.path.join(run_dir, "events.rank1.jsonl"), "w") as f:
        f.write(json.dumps({"ts": 1, "rank": 1, "generation": 0,
                            "event": "anomaly", "kind": "loss_nan"}) + "\n")
    summary = merge_run_dir(run_dir, write=False)
    assert summary["anomalies"] == {"step_time_spike": 3, "loss_nan": 1}


# ===========================================================================
# doctor: gap attribution + report
# ===========================================================================

def _synth_summary(mean=0.4, count=400, skips=5, compile_s=30.0,
                   coll_bytes=8.0e9, n_ranks=4):
    return {
        "ranks": list(range(n_ranks)),
        "step_time": {"count": count, "sum_seconds": mean * count,
                      "mean_seconds": mean, "min_seconds": mean,
                      "max_seconds": mean, "per_rank": {}},
        "loss_scale_skips": skips,
        "compile": {"count": n_ranks, "seconds": compile_s},
        "collective_bytes": {"all_reduce": coll_bytes},
        "tokens_per_sec": {f"{r}:g0:p": 30000.0 for r in range(n_ranks)},
        "mfu": {f"{r}:g0:p": 0.4 for r in range(n_ranks)},
        "anomalies": {}, "events": {}, "exit_codes": {},
        "corrupt_lines": 0, "straggler": None, "restarts": 0,
        "peak_memory_bytes": 0,
    }


_PRED = {"predicted_step_ms": 285.9, "predicted_bound": "compute",
         "predicted_tokens_per_sec_per_chip": 42700.0,
         "predicted_mfu": 0.53, "chip_assumed": "v5e",
         "comm_mb_per_chip": 12.0}


def test_attribution_buckets_sum_to_the_delta():
    """Acceptance: the compute/HBM/comm/compile/skips attribution sums
    to the measured−predicted step-time delta (within 10%; exact by
    construction here)."""
    attr = doctor.attribute_gap(_synth_summary(), _PRED)
    total = sum(attr["buckets"].values())
    assert total == pytest.approx(attr["delta_ms"], abs=0.01)
    assert abs(total - attr["delta_ms"]) <= 0.1 * abs(attr["delta_ms"])
    assert set(attr["buckets"]) == {"compute", "hbm", "comm", "compile",
                                    "skips"}
    # sanity of the individual buckets against hand math
    useful = 400 - 5
    assert attr["buckets"]["compile"] == pytest.approx(
        30.0 / useful * 1e3, abs=0.01)
    assert attr["buckets"]["skips"] == pytest.approx(
        400.0 * 5 / useful, abs=0.01)
    assert attr["measured_ms"] == pytest.approx(
        (0.4 * 400 + 30.0) / useful * 1e3, abs=0.01)


def test_attribution_memory_bound_residual_goes_to_hbm():
    pred = dict(_PRED, predicted_bound="memory")
    attr = doctor.attribute_gap(_synth_summary(), pred)
    assert attr["residual_assigned_to"] == "hbm"
    assert attr["buckets"]["hbm"] != 0.0 and attr["buckets"]["compute"] == 0.0


def test_attribution_handles_missing_inputs():
    assert doctor.attribute_gap(_synth_summary(), None) is None
    empty = _synth_summary(count=0)
    empty["step_time"]["count"] = 0
    assert doctor.attribute_gap(empty, _PRED) is None
    no_eager = _synth_summary(coll_bytes=0.0)
    no_eager["collective_bytes"] = {}
    attr = doctor.attribute_gap(no_eager, _PRED)
    assert attr["buckets"]["comm"] == 0.0 and attr["notes"]


def test_doctor_on_fixture_names_straggler_and_attributes(tmp_path):
    """Acceptance: the checked-in fixture run (seeded 2x straggler rank,
    torn rank-3 stream, predicted row) produces the full diagnosis; the
    straggler is named in the report AND in run_summary.json."""
    run_dir = str(tmp_path / "run")
    shutil.copytree(FIXTURE, run_dir)
    report = doctor.diagnose_run_dir(run_dir)
    attr = report["attribution"]
    assert attr is not None
    assert sum(attr["buckets"].values()) == pytest.approx(
        attr["delta_ms"], abs=0.01)
    kinds = {f["kind"]: f for f in report["findings"]}
    assert "straggler" in kinds and "rank 2" in kinds["straggler"]["detail"]
    assert "torn_telemetry" in kinds
    assert "flight_dump" in kinds
    text = doctor.format_report(report)
    assert "gap attribution" in text and "rank 2" in text
    on_disk = json.load(open(os.path.join(run_dir, "run_summary.json")))
    assert on_disk["straggler"]["rank"] == 2
    assert on_disk["corrupt_lines"] == 1


def test_perf_doctor_cli_over_fixture(tmp_path, capsys):
    from tools.perf_doctor import main as doctor_main
    run_dir = str(tmp_path / "run")
    shutil.copytree(FIXTURE, run_dir)
    assert doctor_main([run_dir]) == 0
    out = capsys.readouterr().out
    assert "gap attribution" in out
    assert "rank 2" in out and "straggler" in out
    # --strict: the fixture's crit findings (straggler) flip the rc
    assert doctor_main([run_dir, "--strict"]) == 1
    capsys.readouterr()   # drain the strict run's text report
    # --json is machine-readable
    assert doctor_main([run_dir, "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["summary"]["straggler"]["rank"] == 2
    # the repo fixture itself is read-only for the default invocation
    # used by the verify gate (--no-write)
    assert doctor_main([FIXTURE, "--no-write"]) == 0
    assert not os.path.exists(os.path.join(FIXTURE, "run_summary.json"))


def test_quick_verdict_classifications():
    assert doctor.quick_verdict(None)["verdict"] == "no-steps"
    assert doctor.quick_verdict([0.1] * 8)["verdict"] == "ok"
    assert doctor.quick_verdict([0.1] * 8,
                                compile_s=10.0)["verdict"] == \
        "compile-dominated"
    v = doctor.quick_verdict([0.1] * 7 + [0.5])
    assert v["verdict"] == "jittery" and v["p95_over_p50"] == 5.0
    assert doctor.quick_verdict([0.1] * 8,
                                anomalies=2)["verdict"] == "anomalous"


def test_quick_verdict_host_async_times_are_not_classified():
    """Dispatch-latency step times (the device drained in a trailing
    sync) must not be mistaken for compile dominance or jitter."""
    times = [0.0001] * 7 + [0.0005]  # enqueue jitter, p95/p50 = 5
    assert doctor.quick_verdict(times, compile_s=2.0,
                                wall_s=10.0)["verdict"] == "host-async"
    # when the times DO account for the wall clock, classification runs
    assert doctor.quick_verdict([1.0] * 10, compile_s=0.1,
                                wall_s=10.5)["verdict"] == "ok"


def test_load_predicted_multi_config_jsonl_and_array(tmp_path):
    """`predict --configs a,b` redirected to a file is JSONL (one row
    per line); the first row carrying a prediction wins. A JSON array
    works too."""
    rows = [{"note": "header, no prediction"},
            {"metric": "gpt_345m_predicted",
             "extras": {"predicted_step_ms": 42.0}},
            {"predicted_step_ms": 99.0}]
    jl = tmp_path / "predicted.jsonl"
    jl.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
    assert doctor.load_predicted(str(jl))["predicted_step_ms"] == 42.0
    ar = tmp_path / "predicted_arr.json"
    ar.write_text(json.dumps(rows))
    assert doctor.load_predicted(str(ar))["predicted_step_ms"] == 42.0


# ===========================================================================
# hot-path wiring
# ===========================================================================

def test_record_train_step_feeds_flight_and_anomaly(monkeypatch):
    monkeypatch.delenv("PADDLE_TELEMETRY_DIR", raising=False)
    base = _counter_value("paddle_anomalies_total", path="wire_test")
    for _ in range(24):
        obs.record_train_step(0.05, tokens=10, path="wire_test", loss=1.5)
    obs.record_train_step(2.0, tokens=10, path="wire_test", loss=1.5)
    assert _counter_value("paddle_anomalies_total",
                          path="wire_test") == base + 1
    steps = [r for r in flight.get_flight_recorder().records()
             if r["kind"] == "step" and r.get("path") == "wire_test"]
    assert len(steps) == 25
    assert steps[-1]["seconds"] == pytest.approx(2.0)
    assert steps[-1]["tokens_per_sec"] == pytest.approx(5.0)


def test_parallel_train_step_records_into_flight(monkeypatch):
    import paddle_tpu as paddle
    from paddle_tpu import nn, optimizer as opt
    from paddle_tpu.distributed import mesh as mesh_mod
    from paddle_tpu.distributed.fleet.train_step import ParallelTrainStep
    from paddle_tpu.distributed.mesh import HybridCommunicateGroup

    monkeypatch.delenv("PADDLE_TELEMETRY_DIR", raising=False)
    saved = (mesh_mod._global_mesh, mesh_mod._hcg)
    try:
        mesh_mod._global_mesh, mesh_mod._hcg = None, None
        hcg = HybridCommunicateGroup(dp_degree=2, mp_degree=1)
        model = nn.Linear(4, 4)
        step = ParallelTrainStep(
            model, opt.SGD(learning_rate=0.1,
                           parameters=model.parameters()),
            lambda m, x, y: (lambda d: (d * d).mean())(m(x) - y), hcg=hcg)
        rng = np.random.default_rng(0)
        x = paddle.to_tensor(rng.standard_normal((4, 4)).astype(np.float32))
        y = paddle.to_tensor(rng.standard_normal((4, 4)).astype(np.float32))
        for _ in range(3):
            step(x, y)
    finally:
        mesh_mod._global_mesh, mesh_mod._hcg = saved

    recs = flight.get_flight_recorder().records()
    compiles = [r for r in recs if r["kind"] == "compile"
                and "ParallelTrainStep" in r["what"]]
    assert len(compiles) >= 2                 # build + first_call
    steps = [r for r in recs if r["kind"] == "step"
             and r.get("path") == "parallel"]
    assert len(steps) == 2                    # first call is compile-labeled
    # the raw device-scalar loss resolves at dump time
    from paddle_tpu.observability.flight import _resolve
    assert isinstance(_resolve(steps[-1]["loss"]), float)


# ===========================================================================
# bench_compare / trace_summary --diff satellites
# ===========================================================================

def _artifact(tmp_path, name, rows):
    path = str(tmp_path / name)
    with open(path, "w") as f:
        json.dump({"tail": "\n".join(json.dumps(r) for r in rows)}, f)
    return path


def _row(metric, value, unit="tokens/s/chip"):
    return {"metric": metric, "value": value, "unit": unit,
            "vs_baseline": 1.0, "extras": {}}


def test_bench_compare_predicted_rows_are_tight_anchors(tmp_path, capsys):
    from tools.bench_compare import main as bc_main
    a = _artifact(tmp_path, "a.json", [
        _row("gpt_345m_tokens_per_sec_per_chip", 43000.0),
        _row("gpt_345m_predicted", 42700.0),
        _row("gpt_1p3b_SKIPPED", 0.0, unit="skipped")])
    b = _artifact(tmp_path, "b.json", [
        _row("gpt_345m_tokens_per_sec_per_chip", 30000.0),   # -30%: noise
        _row("gpt_345m_predicted", 40000.0)])                # -6.3%: real
    assert bc_main([a, b]) == 1
    out = capsys.readouterr().out
    assert "gpt_345m_predicted" in out and "REGRESSION" in out
    # the measured drop stays under the 40% container-variance threshold
    assert out.count("REGRESSION") == 1
    assert "vs-predicted" in out          # anchor-normalized view shown


def test_bench_compare_clean_and_lower_is_better(tmp_path, capsys):
    from tools.bench_compare import main as bc_main
    a = _artifact(tmp_path, "a.json", [
        _row("gpt_345m_predicted", 42700.0),
        _row("gpt_345m_decode_ms_per_token", 8.0, unit="ms/token")])
    b_ok = _artifact(tmp_path, "b.json", [
        _row("gpt_345m_predicted", 43500.0),                 # improvement
        _row("gpt_345m_decode_ms_per_token", 9.0, unit="ms/token")])
    assert bc_main([a, b_ok]) == 0
    b_bad = _artifact(tmp_path, "c.json", [
        _row("gpt_345m_predicted", 42700.0),
        _row("gpt_345m_decode_ms_per_token", 13.0, unit="ms/token")])
    capsys.readouterr()
    assert bc_main([a, b_bad]) == 1
    assert "REGRESSION" in capsys.readouterr().out


def test_bench_compare_unreadable_artifact_rc2(tmp_path, capsys):
    from tools.bench_compare import main as bc_main
    bad = str(tmp_path / "bad.json")
    open(bad, "w").write("not json")
    ok = _artifact(tmp_path, "ok.json", [_row("m", 1.0)])
    assert bc_main([bad, ok]) == 2


def test_trace_summary_diff_top_deltas(tmp_path, capsys):
    from tools.trace_summary import main as ts_main

    def trace(path, spans):
        with open(path, "w") as f:
            json.dump({"traceEvents": [
                {"ph": "X", "name": n, "dur": d, "ts": 0}
                for n, d in spans]}, f)
        return path

    a = trace(str(tmp_path / "a.json"),
              [("matmul", 1000), ("matmul", 1000), ("ln", 100)])
    b = trace(str(tmp_path / "b.json"),
              [("matmul", 2500), ("matmul", 2500), ("ln", 110),
               ("newop", 50)])
    assert ts_main(["--diff", a, b, "--top", "2"]) == 0
    out = capsys.readouterr().out
    lines = [l for l in out.splitlines() if l.strip()]
    # matmul moved the most -> first data row; newop appears from zero
    assert lines[3].startswith("matmul") and "+3.000" in lines[3]
    assert "1 more name(s)" in out
    with pytest.raises(SystemExit):
        ts_main(["--diff", a])              # exactly two traces required


def test_bench_step_telemetry_embeds_doctor_verdict():
    sys.path.insert(0, REPO)
    import bench
    t = bench._StepTelemetry()
    extras = t.extras([0.1] * 5, wall_s=0.5)
    assert extras["doctor"]["verdict"] in ("ok", "anomalous")
    assert "anomalies" in extras["doctor"]
