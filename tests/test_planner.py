"""Cost-model parallelism planner + compiled auto-parallel Engine.

Planner (distributed/auto_parallel/planner.py): legal-factorization
enumeration, closed-form + memory-pass OOM pruning, monotonicity in
devices, the 13B planner-vs-hand ranking the bench row asserts, the
tools/plan.py --json round trip, and the serving-side search.
Engine: pjit-compiled fit with loss parity against hapi compiled-fit
on the 4-device virtual mesh, plan= execution, partition rules, and
the DataLoader/batch_size contract.
"""
import json
import os
import subprocess
import sys

import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer as opt
from paddle_tpu.distributed import mesh as mesh_mod
from paddle_tpu.distributed.auto_parallel import (
    Engine, Plan, Planner, match_partition_rules, plan_gpt,
    plan_serving, price_config,
)
from paddle_tpu.models.gpt import (gpt_13b_config, gpt_345m_config,
                                   gpt_tiny_config)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BF16 = dict(compute_dtype="bfloat16", param_dtype="bfloat16",
            moment_dtype="bfloat16")


@pytest.fixture(autouse=True)
def reset_mesh():
    saved = (mesh_mod._global_mesh, mesh_mod._hcg)
    yield
    mesh_mod._global_mesh, mesh_mod._hcg = saved


# ---------------------------------------------------------------------------
# enumeration
# ---------------------------------------------------------------------------

def test_legal_factorization_enumeration():
    """dp*mp*pp*sharding == N; indivisible head/layer/vocab counts and
    batch splits are rejected before any pricing."""
    cfg = gpt_tiny_config()  # 4 heads, 4 layers, vocab 256
    p = Planner(cfg, 8, global_batch=8)
    cands = list(p.candidates())
    assert cands
    for c in cands:
        assert c["dp"] * c["mp"] * c["pp"] * c["sharding"] == 8
        assert cfg.num_heads % c["mp"] == 0
        assert cfg.num_layers % c["pp"] == 0
        # batch divides replicas x micro-batches
        assert 8 % (c["dp"] * c["sharding"]) == 0
        per_replica = 8 // (c["dp"] * c["sharding"])
        assert per_replica % c["n_micro"] == 0
    # 4 heads: mp=8 illegal; 4 layers: pp=8 illegal
    assert not any(c["mp"] == 8 for c in cands)
    assert not any(c["pp"] == 8 for c in cands)
    # mp=2/pp=2 legal splits ARE present
    assert any(c["mp"] == 2 for c in cands)
    assert any(c["pp"] == 2 for c in cands)
    # indivisible heads kill the whole mp>1 column
    cfg3 = gpt_tiny_config(num_heads=1, hidden_size=64)
    cands3 = list(Planner(cfg3, 8, global_batch=8).candidates())
    assert cands3 and all(c["mp"] == 1 for c in cands3)


def test_pp_needs_enough_micro_batches():
    cfg = gpt_tiny_config()
    p = Planner(cfg, 8, global_batch=8, n_micro_choices=(1, 2, 4))
    for c in p.candidates():
        if c["pp"] > 1:
            assert c["n_micro"] >= c["pp"]


# ---------------------------------------------------------------------------
# OOM pruning
# ---------------------------------------------------------------------------

def test_oom_pruned_closed_form_before_trace():
    """13B on one 16GB chip: params+moments alone overflow — every
    candidate dies in the closed-form prune, no trace, and best raises
    the no-feasible-strategy error."""
    rep = Planner(gpt_13b_config(), 1, chip="v5e", global_batch=8,
                  seq_len=2048, step_kw=BF16).search()
    assert rep.n_traced == 0 and not rep.plans and rep.pruned
    assert all("exceeds" in p.reject_reason for p in rep.pruned)
    with pytest.raises(RuntimeError, match="feasible"):
        rep.best


def test_oom_pruned_by_memory_pass():
    """A config whose weights fit but whose traced activation peak
    overflows is rejected by the liveness memory pass (PTMM001), not
    silently ranked."""
    plan = price_config(gpt_345m_config(max_position_embeddings=1024,
                                        num_heads=8),
                        dict(sharding=8), n_micro=1, remat=False,
                        global_batch=64, seq_len=1024, chip="v5e",
                        step_kw=dict(compute_dtype="bfloat16"))
    assert plan.traced and not plan.feasible
    assert "PTMM001" in plan.reject_reason
    assert plan.peak_hbm_bytes > 14.4 * 1024 ** 3


def test_search_never_returns_infeasible():
    rep = plan_gpt("gpt_345m", devices=8, global_batch=64, max_traces=6)
    assert rep.plans
    budget = 16 * 1024 ** 3 * 0.9
    assert all(p.feasible and p.peak_hbm_bytes <= budget
               for p in rep.plans)
    # ranked fastest-first
    times = [p.step_ms for p in rep.plans]
    assert times == sorted(times)


# ---------------------------------------------------------------------------
# monotonicity
# ---------------------------------------------------------------------------

def test_more_devices_never_predicts_slower():
    """Same model, same global batch: the best plan on 2N devices must
    not predict a slower step than the best plan on N."""
    best_ms = []
    for n in (2, 4, 8):
        rep = plan_gpt("gpt_tiny", devices=n, global_batch=8,
                       max_traces=12)
        best_ms.append(rep.best.step_ms)
    assert best_ms[1] <= best_ms[0] * 1.001
    assert best_ms[2] <= best_ms[1] * 1.001


# ---------------------------------------------------------------------------
# planner vs hand-written 13B (the acceptance assertion)
# ---------------------------------------------------------------------------

def test_planner_beats_handwritten_13b_config():
    """The planner's best 13B config on the bench's 16-device slice
    must beat the hand-written bench config (mp4 x pp4, n_micro 16,
    full remat, 1f1b) in predicted MFU — priced by the same trace-based
    cost model on both sides (the gpt_13b_planned_predicted bench row's
    claim)."""
    hand = price_config(gpt_13b_config(), dict(mp=4, pp=4), n_micro=16,
                        remat=True, pipeline_schedule="1f1b",
                        global_batch=16, seq_len=2048, chip="v5e",
                        step_kw=BF16)
    assert hand.feasible  # the hand config itself fits the chip
    rep = plan_gpt("gpt_13b", devices=16, chip="v5e", max_traces=12)
    best = rep.best
    assert best.feasible
    assert best.predicted_mfu > hand.predicted_mfu
    assert best.step_ms < hand.step_ms
    assert rep.planner_s < 120  # planning is seconds, not minutes
    # both sides price per-device roofline on the same chip table
    assert best.chip == hand.chip == "v5e"


def test_price_config_matches_search_scoring():
    """The hand-priced row and the search's own trace of the same
    config must agree exactly (one scorer, two entry points)."""
    cfg = gpt_tiny_config()
    hand = price_config(cfg, dict(mp=2, pp=2), n_micro=4, remat=True,
                        global_batch=8, seq_len=128,
                        step_kw=dict(compute_dtype="bfloat16"))
    p = Planner(cfg, 4, global_batch=8, seq_len=128,
                step_kw=dict(compute_dtype="bfloat16"))
    plan = p._trace_plan(dict(dp=1, mp=2, pp=2, sharding=1, n_micro=4,
                              remat=True))
    assert plan.step_ms == pytest.approx(hand.step_ms, rel=1e-9)
    assert plan.peak_hbm_bytes == pytest.approx(hand.peak_hbm_bytes)


# ---------------------------------------------------------------------------
# tools/plan.py round trip
# ---------------------------------------------------------------------------

def test_plan_cli_json_round_trip():
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "plan.py"),
         "--model", "gpt_tiny", "--devices", "4", "--max-traces", "4",
         "--json"],
        capture_output=True, text=True, timeout=300, cwd=REPO)
    assert r.returncode == 0, r.stderr[-800:]
    doc = json.loads(r.stdout.splitlines()[-1])
    assert doc["model"] == "gpt_tiny" and doc["n_devices"] == 4
    assert doc["plans"] and doc["best"]
    for key in ("mesh", "n_micro", "remat", "step_ms", "predicted_mfu",
                "peak_hbm_gb", "bound", "wire_dtype"):
        assert key in doc["best"]
    assert doc["planner_s"] > 0
    # the CLI's winner is the in-process winner (deterministic search)
    rep = plan_gpt("gpt_tiny", devices=4, max_traces=4)
    assert doc["best"]["mesh"] == rep.best.mesh
    # as_dict rounds to 3 decimals for the artifact
    assert doc["best"]["step_ms"] == pytest.approx(rep.best.step_ms,
                                                   abs=5e-4)
    # and the best entry round-trips into an executable mesh spec
    degrees = {k: doc["best"][k] for k in ("dp", "mp", "pp", "sharding")}
    assert int(np.prod(list(degrees.values()))) == 4


# ---------------------------------------------------------------------------
# serving-side search
# ---------------------------------------------------------------------------

def test_plan_serving_ranks_and_prunes():
    out = plan_serving("tiny", chip="v5e",
                       concurrency_choices=(4, 16),
                       page_sizes=(32, 64),
                       quantize_choices=(None, "int8"), top_k=8)
    assert out["plans"] and out["best"]
    tps = [r["predicted_tokens_per_sec"] for r in out["plans"]]
    assert tps == sorted(tps, reverse=True)
    assert all(r["feasible"] for r in out["plans"])
    for key in ("concurrency", "page_size", "quantize", "hbm_mb",
                "predicted_decode_step_ms"):
        assert key in out["best"]
    # 13B fp weights (~26GB) can never fit a v5e chip: all pruned
    out13 = plan_serving("13b", chip="v5e", concurrency_choices=(4,),
                         page_sizes=(64,), quantize_choices=(None,))
    assert out13["best"] is None and out13["n_pruned"] == 1


# ---------------------------------------------------------------------------
# one chip table
# ---------------------------------------------------------------------------

def test_cluster_delegates_to_chip_specs():
    from paddle_tpu.distributed.auto_parallel import Cluster
    from paddle_tpu.observability.instrument import chip_specs
    for kind in ("v5e", "v5p"):
        c = Cluster.from_chip(kind, 8)
        s = chip_specs(kind)
        assert c.peak_flops == s["peak_flops"]
        assert c.hbm_bandwidth == s["hbm_bw"]
        assert c.ici_bandwidth == s["ici_bw"]
        assert c.hbm_bytes == s["hbm_gb"] * 1024 ** 3
        assert c.name == kind
    assert Cluster.v5e(4).peak_flops == chip_specs("v5e")["peak_flops"]


# ---------------------------------------------------------------------------
# Engine: compiled fit
# ---------------------------------------------------------------------------

def _toy_data(n=64, d=8, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n, d)).astype(np.float32)
    y = (x @ rng.standard_normal((d, 1))).astype(np.float32)
    return x, y


def _dataset(x, y):
    class DS(paddle.io.Dataset):
        def __len__(self):
            return len(x)

        def __getitem__(self, i):
            return x[i], y[i]
    return DS()


def test_engine_fit_loss_parity_with_hapi_compiled_fit():
    """Engine.fit runs the pjit-compiled planned step: per-step losses
    must match hapi Model.fit's compiled path exactly on the 4-device
    virtual mesh (same ParallelTrainStep, same program)."""
    from paddle_tpu.distributed.mesh import HybridCommunicateGroup
    x, y = _toy_data()

    def run_hapi():
        mesh_mod._global_mesh, mesh_mod._hcg = None, None
        HybridCommunicateGroup(dp_degree=4)
        paddle.seed(0)
        net = nn.Linear(8, 1)
        model = paddle.Model(net)
        model.prepare(opt.SGD(learning_rate=0.1,
                              parameters=net.parameters()),
                      nn.MSELoss())
        losses = []

        class Rec(paddle.hapi.callbacks.Callback):
            def on_train_batch_end(self, step, logs=None):
                losses.append(logs["loss"][0])
        model.fit(_dataset(x, y), epochs=2, batch_size=16, verbose=0,
                  shuffle=False, callbacks=[Rec()])
        assert model._parallel_step is not None
        return losses

    def run_engine():
        mesh_mod._global_mesh, mesh_mod._hcg = None, None
        HybridCommunicateGroup(dp_degree=4)
        paddle.seed(0)
        net = nn.Linear(8, 1)
        eng = Engine(net, loss=nn.MSELoss(),
                     optimizer=opt.SGD(learning_rate=0.1,
                                       parameters=net.parameters()))
        eng.prepare()
        logs = eng.fit(_dataset(x, y), batch_size=16, epochs=2,
                       verbose=0, shuffle=False)
        assert eng._parallel_step is not None, \
            "Engine.fit did not take the compiled path"
        return logs["loss"]

    hapi_losses = run_hapi()
    engine_losses = run_engine()
    assert len(hapi_losses) == len(engine_losses) == 8
    np.testing.assert_allclose(engine_losses, hapi_losses,
                               rtol=1e-6, atol=1e-7)
    # it trained, not just matched
    assert engine_losses[-1] < engine_losses[0] * 0.5


def test_engine_fit_with_plan_executes_plan_mesh():
    """prepare(plan=) builds the plan's hybrid mesh over the real
    devices and fit runs the compiled, donated step on it."""
    x, y = _toy_data()
    mesh_mod._global_mesh, mesh_mod._hcg = None, None
    paddle.seed(0)
    net = nn.Linear(8, 1)
    eng = Engine(net, loss=nn.MSELoss(),
                 optimizer=opt.SGD(learning_rate=0.1,
                                   parameters=net.parameters()))
    eng.prepare(plan=Plan(dp=2, sharding=2))
    logs = eng.fit(_dataset(x, y), batch_size=16, epochs=2, verbose=0,
                   shuffle=False)
    step = eng._parallel_step
    assert step is not None
    assert dict(step.mesh.shape)["dp"] == 2
    assert dict(step.mesh.shape)["sharding"] == 2
    assert step.donate  # the plan's donation choice rides through
    assert logs["loss"][-1] < logs["loss"][0] * 0.5


def test_engine_partition_rules_shard_params():
    """fmengine-style regex rules annotate un-annotated parameters; the
    compiled step lays them out accordingly (GSPMD does the rest)."""
    mesh_mod._global_mesh, mesh_mod._hcg = None, None
    paddle.seed(0)
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 1))
    x, y = _toy_data()
    eng = Engine(net, loss=nn.MSELoss(),
                 optimizer=opt.SGD(learning_rate=0.1,
                                   parameters=net.parameters()))
    eng.prepare(plan=Plan(dp=2, mp=2),
                partition_rules=[(r"0\.weight", (None, "mp"))])
    eng.fit(_dataset(x, y), batch_size=16, epochs=1, verbose=0,
            shuffle=False)
    w0 = net[0].weight
    assert w0.sharding_spec == P(None, "mp")
    assert w0._value.sharding.spec == P(None, "mp")
    # the (16, 1) head stays replicated (no rule matched)
    assert getattr(net[2].weight, "sharding_spec", None) in (None, P())


def test_match_partition_rules_degrades_cleanly():
    """A matched axis the mesh lacks (or that doesn't divide the dim)
    is dropped to replicated instead of crashing GSPMD."""
    from paddle_tpu.distributed.auto_parallel import ProcessMesh
    pm = ProcessMesh([[0, 1], [2, 3]], dim_names=["x", "y"])
    lin = nn.Linear(8, 6)  # 6 % 4 != 0
    specs = match_partition_rules(
        [(r"weight", (None, ("x", "y"))), (r"bias", ("nope",))],
        [("weight", lin.weight), ("bias", lin.bias)], pm.jax_mesh)
    assert specs["weight"] == P(None, None)   # 6 % (2*2) != 0 -> drop
    assert specs["bias"] == P(None)           # unknown axis -> drop
    lin2 = nn.Linear(8, 8)
    specs2 = match_partition_rules(
        [(r"weight", (None, "y"))],
        [("weight", lin2.weight)], pm.jax_mesh)
    assert specs2["weight"] == P(None, "y")   # 8 % 2 == 0 -> kept


def test_engine_fit_indivisible_batch_stays_eager():
    """A dataset whose batching can't divide the mesh (odd batch size,
    or a partial tail batch with drop_last=False) must train eagerly
    end to end — never crash mid-epoch in pjit — and drop_last=True
    restores the compiled path (review finding, PR 12)."""
    from paddle_tpu.distributed.mesh import HybridCommunicateGroup
    x, y = _toy_data(n=66)  # 66 % 16 = 2-row tail, 2 % 8 != 0
    mesh_mod._global_mesh, mesh_mod._hcg = None, None
    HybridCommunicateGroup(dp_degree=8)
    paddle.seed(0)
    net = nn.Linear(8, 1)
    eng = Engine(net, loss=nn.MSELoss(),
                 optimizer=opt.SGD(learning_rate=0.05,
                                   parameters=net.parameters()))
    eng.prepare()
    logs = eng.fit(_dataset(x, y), batch_size=16, epochs=2, verbose=0,
                   shuffle=False)
    assert eng._parallel_step is None  # proven indivisible -> eager
    assert len(logs["loss"]) == 10 and logs["loss"][-1] < logs["loss"][0]
    # drop_last=True makes every batch divisible: compiled path engages
    paddle.seed(0)
    net2 = nn.Linear(8, 1)
    eng2 = Engine(net2, loss=nn.MSELoss(),
                  optimizer=opt.SGD(learning_rate=0.05,
                                    parameters=net2.parameters()))
    eng2.prepare()
    logs2 = eng2.fit(_dataset(x, y), batch_size=16, epochs=2, verbose=0,
                     shuffle=False, drop_last=True)
    assert eng2._parallel_step is not None
    assert len(logs2["loss"]) == 8  # 4 full batches x 2 epochs


def test_engine_save_syncs_compiled_optimizer_state(tmp_path):
    """After a compiled fit the live Adam moments sit in the step
    object; Engine.save must sync them back so a resume doesn't restart
    from the build-time zeros (review finding, PR 12)."""
    from paddle_tpu.distributed.mesh import HybridCommunicateGroup
    x, y = _toy_data(n=32)
    mesh_mod._global_mesh, mesh_mod._hcg = None, None
    HybridCommunicateGroup(dp_degree=4)
    paddle.seed(0)
    net = nn.Linear(8, 1)
    adam = opt.Adam(learning_rate=1e-2, parameters=net.parameters())
    eng = Engine(net, loss=nn.MSELoss(), optimizer=adam)
    eng.prepare()
    eng.fit(_dataset(x, y), batch_size=16, epochs=2, verbose=0,
            shuffle=False)
    assert eng._parallel_step is not None
    eng.save(str(tmp_path / "ckpt"))
    from paddle_tpu.framework import io as io_mod
    state = io_mod.load(str(tmp_path / "ckpt") + ".pdopt")
    moments = [np.asarray(v) for k, v in state.items()
               if "moment" in str(k).lower()]
    assert moments, f"no moment accumulators persisted: {list(state)}"
    assert any(np.abs(m).max() > 0 for m in moments), \
        "persisted Adam moments are the stale build-time zeros"


def test_engine_prepare_rejects_plan_plus_mesh():
    from paddle_tpu.distributed.auto_parallel import ProcessMesh
    eng = Engine(nn.Linear(4, 1))
    with pytest.raises(ValueError, match="not both"):
        eng.prepare(plan=Plan(dp=2),
                    mesh=ProcessMesh([0, 1], dim_names=["dp"]))


def test_tuner_heads_fallback_always_divides():
    """ModelSpec hiddens that aren't 64-multiples still tune (the
    legacy closed-form surface accepted them)."""
    from paddle_tpu.distributed.auto_parallel import Cluster, ModelSpec
    from paddle_tpu.distributed.auto_parallel.tuner import (
        ParallelTuner, _config_from_spec)
    for hidden in (1000, 96, 1024, 5120):
        cfg = _config_from_spec(ModelSpec(hidden=hidden, layers=2,
                                          seq_len=64, vocab_size=128))
        assert cfg.hidden_size % cfg.num_heads == 0
    best = ParallelTuner(
        ModelSpec(hidden=1000, layers=2, seq_len=64, vocab_size=128),
        Cluster.v5e(4), global_batch=8, max_traces=2).tune()
    assert best.cost.time_ms > 0


def test_engine_loader_contract():
    """A DataLoader passes through untouched (its own batch size wins);
    datasets wrap with the caller's batch_size + shuffle."""
    x, y = _toy_data(n=32)
    mesh_mod._global_mesh, mesh_mod._hcg = None, None
    paddle.seed(0)
    net = nn.Linear(8, 1)
    eng = Engine(net, loss=nn.MSELoss(),
                 optimizer=opt.SGD(learning_rate=0.1,
                                   parameters=net.parameters()))
    # no mesh at all: eager fallback still honors the contract
    loader = paddle.io.DataLoader(_dataset(x, y), batch_size=8,
                                  shuffle=False)
    logs = eng.fit(loader, batch_size=999, epochs=1, verbose=0)
    assert len(logs["loss"]) == 4  # 32/8 — loader's batching, not 999
    logs = eng.fit(_dataset(x, y), batch_size=16, epochs=1, verbose=0,
                   shuffle=False)
    assert len(logs["loss"]) == 2  # 32/16 — caller batch_size honored
