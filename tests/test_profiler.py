"""Profiler tests.

Parity model: reference unittests/test_profiler.py — scheduler state
transitions, RecordEvent capture, chrome-trace export round-trip, summary
aggregation.
"""
import json
import os

import numpy as np

import paddle_tpu as paddle
from paddle_tpu import profiler
from paddle_tpu.profiler import (
    Profiler, ProfilerState, RecordEvent, make_scheduler,
    export_chrome_tracing, load_profiler_result, benchmark,
)


def test_make_scheduler_states():
    sched = make_scheduler(closed=1, ready=1, record=2, repeat=1,
                           skip_first=1)
    states = [sched(i) for i in range(6)]
    assert states == [
        ProfilerState.CLOSED,             # skip_first
        ProfilerState.CLOSED,             # closed
        ProfilerState.READY,              # ready
        ProfilerState.RECORD,             # record
        ProfilerState.RECORD_AND_RETURN,  # last record step
        ProfilerState.CLOSED,             # repeat exhausted
    ]


def test_record_and_export(tmp_path):
    traces = []
    p = Profiler(scheduler=(0, 3),
                 on_trace_ready=lambda prof: traces.append(prof),
                 targets=[profiler.ProfilerTarget.CPU])
    p.start()
    x = paddle.to_tensor(np.ones((8, 8), np.float32))
    for _ in range(3):
        with RecordEvent("matmul_step"):
            y = paddle.matmul(x, x)
        p.step()
    p.stop()
    assert traces, "on_trace_ready never fired"
    path = str(tmp_path / "trace.json")
    p.export(path)
    data = load_profiler_result(path)
    names = {e["name"] for e in data["traceEvents"]}
    assert "matmul_step" in names
    stats = p.summary()
    assert stats["matmul_step"]["calls"] == 3


def test_export_chrome_tracing_handler(tmp_path):
    d = str(tmp_path / "traces")
    p = Profiler(scheduler=(0, 2), on_trace_ready=export_chrome_tracing(d),
                 targets=[profiler.ProfilerTarget.CPU])
    p.start()
    for _ in range(2):
        with RecordEvent("step"):
            pass
        p.step()
    p.stop()
    files = os.listdir(d)
    assert any(f.endswith(".paddle_trace.json") for f in files)
    with open(os.path.join(d, files[0])) as f:
        assert "traceEvents" in json.load(f)


def test_events_not_collected_when_closed():
    p = Profiler(scheduler=(5, 6), targets=[profiler.ProfilerTarget.CPU])
    p.start()
    with RecordEvent("should_not_appear"):
        pass
    p.stop()
    assert all(e[0] != "should_not_appear" for e in p._events)


def test_benchmark_timer():
    b = benchmark()
    b.reset()
    b.begin()
    for _ in range(3):
        b.step(num_samples=32)
    b.end()
    r = b.report()
    assert r["ips"] > 0 and r["steps"] >= 3
