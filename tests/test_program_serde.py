"""Program serialization (ProgramDesc parity): round-trip structure,
to_string, executor runs on the deserialized DAG."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import static


def _build_program():
    main = static.Program()
    startup = static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [4, 8], "float32")
        w = paddle.nn.Linear(8, 3)
        y = w(paddle.to_tensor_static(x)) if hasattr(paddle,
                                                     "to_tensor_static") else \
            w(x)
        out = paddle.tanh(y)
    return main, x, out, w


def test_roundtrip_matches_original():
    static.enable_static()
    try:
        main, x, out, lin = _build_program()
        blob = main.serialize_to_string(fetch_vars=[out])
        assert blob[:8] == b"PTPROG01"

        prog2, feeds2, fetches2 = static.deserialize_program(blob)
        assert list(feeds2) == ["x"]
        assert len(fetches2) == 1

        exe = static.Executor()
        feed = {"x": np.random.default_rng(0)
                .standard_normal((4, 8)).astype(np.float32)}
        want = exe.run(main, feed=feed, fetch_list=[out])[0]
        got = static.Executor().run(prog2, feed=feed,
                                    fetch_list=fetches2)[0]
        np.testing.assert_allclose(got, want, rtol=1e-6)
    finally:
        static.disable_static()


def test_save_load_program_file(tmp_path):
    static.enable_static()
    try:
        main, x, out, _ = _build_program()
        path = str(tmp_path / "prog.pdmodel")
        static.save_program(main, path, fetch_vars=[out])
        prog2, feeds2, fetches2 = static.load_program(path)
        feed = {"x": np.ones((4, 8), np.float32)}
        want = static.Executor().run(main, feed=feed, fetch_list=[out])[0]
        got = static.Executor().run(prog2, feed=feed,
                                    fetch_list=fetches2)[0]
        np.testing.assert_allclose(got, want, rtol=1e-6)
    finally:
        static.disable_static()


def test_parse_from_string_and_to_string():
    static.enable_static()
    try:
        main, x, out, _ = _build_program()
        s = main.to_string()
        assert "feed x" in s and "%0" in s
        prog2 = static.Program.parse_from_string(
            main.serialize_to_string())
        assert len(prog2._nodes) == len(main._nodes)
        assert str(prog2).count("%") >= 1
    finally:
        static.disable_static()


def test_closure_op_serializes_by_value():
    static.enable_static()
    try:
        main = static.Program()
        with static.program_guard(main):
            x = static.data("x", [2], "float32")
            scale = 3.0
            from paddle_tpu.framework.tape import apply
            y = apply(lambda v: v * scale, x, op_name="closure_scale")
        blob = main.serialize_to_string(fetch_vars=[y])
        prog2, _, fetches2 = static.deserialize_program(blob)
        out = static.Executor().run(
            prog2, feed={"x": np.array([1.0, 2.0], np.float32)},
            fetch_list=fetches2)[0]
        np.testing.assert_allclose(out, [3.0, 6.0])
    finally:
        static.disable_static()


def test_amp_program_serializes():
    from paddle_tpu import amp
    static.enable_static()
    try:
        main = static.Program()
        with static.program_guard(main):
            x = static.data("x", [4, 8], "float32")
            with amp.auto_cast(enable=True, dtype="bfloat16"):
                lin = paddle.nn.Linear(8, 3)
                out = paddle.tanh(lin(x))
        blob = main.serialize_to_string(fetch_vars=[out])
        prog2, _, fetches2 = static.deserialize_program(blob)
        feed = {"x": np.ones((4, 8), np.float32)}
        want = static.Executor().run(main, feed=feed, fetch_list=[out])[0]
        got = static.Executor().run(prog2, feed=feed,
                                    fetch_list=fetches2)[0]
        np.testing.assert_allclose(got, want, rtol=1e-6)
    finally:
        static.disable_static()


def test_unserializable_capture_raises_clear_error():
    import threading
    static.enable_static()
    try:
        main = static.Program()
        with static.program_guard(main):
            x = static.data("x", [2], "float32")
            lock = threading.Lock()
            from paddle_tpu.framework.tape import apply

            def weird(v):
                assert lock is not None
                return v * 2

            y = apply(weird, x, op_name="locked_op")
        with pytest.raises(ValueError, match="locked_op"):
            main.serialize_to_string(fetch_vars=[y])
    finally:
        static.disable_static()


def test_envelope_rejects_arbitrary_classes():
    """The outer payload envelope must not instantiate arbitrary classes
    (round-2 advice: loading untrusted bytes shouldn't execute at parse
    time — op blobs are gated behind the documented trust model)."""
    import os
    import pickle
    from paddle_tpu.static import serde

    class Evil:
        def __reduce__(self):
            return (os.path.join, ("pwn", "ed"))

    blob = serde._MAGIC + pickle.dumps({"nodes": Evil()})
    with pytest.raises(pickle.UnpicklingError, match="may not reference"):
        serde.deserialize_program(blob)
