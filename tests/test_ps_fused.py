"""Parameter-server stack + fused incubate layers + fleet utils tests.

Parity model: the reference PS tests run against ps_local_client (in-process
tables); fused layer tests compare against the unfused compositions; fs tests
mirror test_fs.py LocalFS cases.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, ops, optimizer as opt
from paddle_tpu.distributed.ps import (
    MemorySparseTable, MemoryDenseTable, SGDAccessor, AdagradAccessor,
    PsLocalClient, TheOnePs, DistributedEmbedding,
)
from paddle_tpu.incubate.nn import (
    FusedMultiHeadAttention, FusedFeedForward, FusedTransformerEncoderLayer,
    FusedEcMoe,
)
from paddle_tpu.distributed.fleet.utils import LocalFS
from paddle_tpu.distributed.fleet import metrics as fleet_metrics


def _np(t):
    return np.asarray(t._value)


# ------------------------------------------------------------------- PS
def test_sparse_table_pull_push_sgd():
    t = MemorySparseTable(4, SGDAccessor(learning_rate=1.0), seed=0)
    rows = t.pull([7, 9, 7])
    assert rows.shape == (3, 4) and t.size == 2
    np.testing.assert_allclose(rows[0], rows[2])  # same id, same row
    before = t.pull([7])[0].copy()
    g = np.ones((3, 4), np.float32)
    t.push([7, 9, 7], g)  # id 7 appears twice → grads accumulate
    after = t.pull([7])[0]
    np.testing.assert_allclose(after, before - 2.0, rtol=1e-6)


def test_sparse_table_adagrad_and_save_load(tmp_path):
    t = MemorySparseTable(4, AdagradAccessor(learning_rate=0.1), seed=1)
    t.pull([1, 2, 3])
    t.push([1, 2], np.ones((2, 4), np.float32))
    path = str(tmp_path / "table")
    t.save(path)
    t2 = MemorySparseTable(4, AdagradAccessor(), seed=2)
    t2.load(path)
    np.testing.assert_allclose(t2.pull([1]), t.pull([1]))
    assert t2.size == 3


def test_dense_table():
    t = MemoryDenseTable((3, 2), SGDAccessor(learning_rate=0.5), seed=0)
    p0 = t.pull()
    t.push(np.ones((3, 2), np.float32))
    np.testing.assert_allclose(t.pull(), p0 - 0.5, rtol=1e-6)


def test_distributed_embedding_trains():
    """PS embedding + device dense layer: CTR-style model converges."""
    paddle.seed(0)
    ps = TheOnePs()
    emb = DistributedEmbedding(ps, emb_dim=8, accessor="adagrad", lr=0.5)
    head = nn.Linear(8, 1)
    o = opt.Adam(learning_rate=1e-2, parameters=head.parameters())
    rng = np.random.default_rng(0)
    ids_np = rng.integers(0, 1000, (64,)).astype(np.int64)
    # target depends on feature id parity — learnable via embeddings
    y_np = (ids_np % 2).astype(np.float32)[:, None]

    losses = []
    for _ in range(60):
        e = emb(paddle.to_tensor(ids_np))
        pred = nn.functional.sigmoid(head(e))
        loss = ops.mean((pred - paddle.to_tensor(y_np)) ** 2)
        loss.backward()
        o.step()
        o.clear_grad()
        losses.append(float(_np(loss)))
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])
    assert emb.table.size <= 1000  # only touched rows exist


def test_ps_local_client_api():
    c = PsLocalClient()
    c.create_sparse_table(0, 4)
    c.create_dense_table(1, (2, 2))
    r = c.pull_sparse(0, [5])
    c.push_sparse_grad(0, [5], np.ones((1, 4), np.float32))
    assert not np.allclose(c.pull_sparse(0, [5]), r)
    d = c.pull_dense(1)
    c.push_dense_grad(1, np.ones((2, 2), np.float32))
    assert not np.allclose(c.pull_dense(1), d)


# ------------------------------------------------------------ fused nn
def test_fused_mha_matches_unfused_shapes_and_grad():
    paddle.seed(1)
    m = FusedMultiHeadAttention(16, 4, dropout_rate=0.0,
                                attn_dropout_rate=0.0)
    x = paddle.to_tensor(
        np.random.default_rng(2).standard_normal((2, 8, 16))
        .astype(np.float32))
    x.stop_gradient = False
    out = m(x)
    assert tuple(out.shape) == (2, 8, 16)
    ops.mean(out * out).backward()
    assert x.grad is not None
    assert m.qkv.weight.grad is not None


def test_fused_encoder_layer_runs():
    paddle.seed(2)
    layer = FusedTransformerEncoderLayer(16, 4, 32, dropout_rate=0.0)
    x = paddle.to_tensor(np.ones((2, 6, 16), np.float32))
    out = layer(x)
    assert tuple(out.shape) == (2, 6, 16)
    assert np.isfinite(_np(out)).all()


def test_fused_ec_moe_matches_dense_mixture():
    paddle.seed(3)
    moe = FusedEcMoe(8, 16, num_experts=3, act_type="gelu")
    x = paddle.to_tensor(
        np.random.default_rng(3).standard_normal((2, 4, 8))
        .astype(np.float32))
    out = moe(x)
    # oracle: explicit loop over experts
    import jax.nn as jnn
    xv = _np(x)
    w = np.asarray(jnn.softmax(np.asarray(_np(moe.gate(x))), axis=-1))
    want = np.zeros_like(xv)
    for e in range(3):
        h = xv @ _np(moe.bmm_weight0)[e] + _np(moe.bmm_bias0)[e]
        h = np.asarray(jnn.gelu(h))
        y = h @ _np(moe.bmm_weight1)[e] + _np(moe.bmm_bias1)[e]
        want += w[..., e:e + 1] * y
    np.testing.assert_allclose(_np(out), want, rtol=2e-4, atol=2e-4)


# ----------------------------------------------------------- fleet utils
def test_local_fs(tmp_path):
    fs = LocalFS()
    d = str(tmp_path / "a")
    fs.mkdirs(d)
    assert fs.is_dir(d) and fs.is_exist(d)
    f = str(tmp_path / "a" / "x.txt")
    fs.touch(f)
    assert fs.is_file(f)
    dirs, files = fs.ls_dir(str(tmp_path / "a"))
    assert files == ["x.txt"]
    fs.mv(f, str(tmp_path / "a" / "y.txt"))
    assert fs.is_file(str(tmp_path / "a" / "y.txt"))
    fs.delete(d)
    assert not fs.is_exist(d)


def test_fleet_metrics():
    assert fleet_metrics.sum(np.array([1.0, 2.0])) == 3.0
    assert fleet_metrics.max(np.array([1.0, 5.0])) == 5.0
    assert fleet_metrics.acc(8, 10) == 0.8
    assert abs(fleet_metrics.mae(np.array([2.0, 2.0]), 4) - 1.0) < 1e-9
    # AUC oracle: perfect separation → 1.0
    pos = np.zeros(10)
    pos[9] = 100  # all positives in the top bucket
    neg = np.zeros(10)
    neg[0] = 100  # all negatives in the bottom bucket
    assert abs(fleet_metrics.auc(pos, neg) - 1.0) < 1e-9


# ---------------------------------------------------------------- CTR + async
def test_ctr_accessor_lifecycle():
    """ctr_accessor.cc parity: show/click score, decay, embedx admission,
    shrink eviction, entry-policy admission."""
    from paddle_tpu.distributed.ps import CtrAccessor, CtrSparseTable
    from paddle_tpu.distributed import CountFilterEntry

    acc = CtrAccessor(learning_rate=0.1, embedx_threshold=3.0,
                      delete_threshold=0.5, delete_after_unseen_days=2,
                      show_click_decay_rate=0.5)
    # count-filter admission: a feature must be seen twice to be created
    table = CtrSparseTable(4, accessor=acc, entry=CountFilterEntry(2))

    ids = np.array([7, 7], np.int64)
    g = np.ones((2, 4), np.float32) * 0.1
    table.push(ids[:1], g[:1])          # 1st sight: rejected
    assert table.size == 0
    table.push(ids[:1], g[:1])          # 2nd sight: admitted
    assert table.size == 1
    row0 = table.pull(np.array([7]))[0].copy()

    # clicks drive the score over the embedx threshold
    assert table.pull_embedx(np.array([7])).max() == 0.0
    table.push(np.array([7]), g[:1], shows=[5.0], clicks=[3.0])
    assert 7 in table._embedx            # score = 0.1*(6-3) + 3 > 3
    assert not np.allclose(table.pull(np.array([7]))[0], row0)

    # shrink: decay halves show/click; two silent days evict
    n0 = table.shrink()
    assert n0 == 0 and table.size == 1
    table._stats[7]["show"] = 0.0        # stale feature
    table._stats[7]["click"] = 0.0
    assert table.shrink() == 1 and table.size == 0


def test_async_communicator_merges_and_flushes():
    """communicator.h AsyncCommunicator: background merge-by-key push."""
    from paddle_tpu.distributed.ps import (Communicator, PsLocalClient,
                                           SGDAccessor)
    client = PsLocalClient()
    client.create_sparse_table(0, 4, accessor=SGDAccessor(1.0),
                               initializer=lambda: np.zeros(4, np.float32))
    comm = Communicator(client, send_wait_times=0.01)
    comm.start()
    try:
        for _ in range(3):  # same id 3x -> one merged update per flush
            comm.push_sparse_async(0, np.array([5]),
                                   np.ones((1, 4), np.float32))
        comm.flush()
        row = client.pull_sparse(0, np.array([5]))[0]
        np.testing.assert_allclose(row, -3.0)  # lr=1: row -= sum(grads)
    finally:
        comm.stop()


def test_geo_communicator_syncs_deltas():
    """communicator.h GeoCommunicator: local drift ships as delta; the
    local copy re-syncs to the server's merged value."""
    from paddle_tpu.distributed.ps import (GeoCommunicator, PsLocalClient,
                                           MemorySparseTable, SGDAccessor)
    client = PsLocalClient()
    # geo server table applies raw deltas: SGD at lr=1
    client.create_sparse_table(1, 2, accessor=SGDAccessor(1.0),
                               initializer=lambda: np.zeros(2, np.float32))
    local = MemorySparseTable(2, accessor=SGDAccessor(0.5),
                              initializer=lambda: np.zeros(2, np.float32))
    geo = GeoCommunicator(client, local, table_id=1)

    ids = np.array([3], np.int64)
    geo.record_touch(ids)
    local.push(ids, np.ones((1, 2), np.float32))   # local -= 0.5
    n = geo.sync_once()
    assert n == 1
    srv = client.pull_sparse(1, ids)[0]
    np.testing.assert_allclose(srv, -0.5)          # delta arrived
    np.testing.assert_allclose(local.pull(ids)[0], srv)  # re-synced
    # second trainer drift composes on the server value
    local.push(ids, np.ones((1, 2), np.float32))
    geo.record_touch(ids)
    geo.sync_once()
    np.testing.assert_allclose(client.pull_sparse(1, ids)[0], -1.0)


def test_ctr_table_save_load_roundtrip(tmp_path):
    """CTR state (stats, embedx, slots) survives save/load; restored
    features never crash push and stay evictable."""
    from paddle_tpu.distributed.ps import CtrAccessor, CtrSparseTable
    acc = CtrAccessor(learning_rate=0.1, embedx_threshold=2.0)
    t = CtrSparseTable(4, accessor=acc)
    t.push(np.array([1, 2]), np.ones((2, 4), np.float32) * 0.1,
           shows=[5, 1], clicks=[3, 0])
    assert 1 in t._embedx
    t.push(np.array([1]), np.ones((1, 4), np.float32) * 0.1,
           embedx_grads=np.ones((1, 4), np.float32))
    assert np.abs(t._embedx[1]).max() > 0  # embedx actually trains
    path = str(tmp_path / "ctr_table")
    t.save(path)

    t2 = CtrSparseTable(4, accessor=acc)
    t2.load(path)
    assert t2._stats[1]["click"] == t._stats[1]["click"]
    np.testing.assert_allclose(t2.pull_embedx(np.array([1])),
                               t.pull_embedx(np.array([1])))
    t2.push(np.array([1]), np.ones((1, 4), np.float32))  # no KeyError
    for _ in range(60):
        t2.shrink()
    assert t2.size == 0  # restored features are evictable


def test_probability_entry_admission():
    from paddle_tpu.distributed.ps import CtrSparseTable
    from paddle_tpu.distributed import ProbabilityEntry
    t = CtrSparseTable(4, entry=ProbabilityEntry(1.0))
    t.push(np.array([9]), np.ones((1, 4), np.float32))
    assert t.size == 1  # p=1 admits; no AttributeError on nonzero fid


def test_multiclass_nms_pixel_convention():
    """normalized=False uses the +1 pixel convention in IoU."""
    import paddle_tpu.vision.ops as vops
    import paddle_tpu as paddle
    # two 1-pixel boxes: normalized math gives zero areas (iou=0, both
    # kept); pixel math gives iou=1 for identical boxes (one suppressed)
    bb = np.array([[[0, 0, 0, 0], [0, 0, 0, 0]]], np.float32)
    sc = np.zeros((1, 2, 2), np.float32)
    sc[0, 1] = [0.9, 0.8]
    _, n_norm = vops.multiclass_nms(
        paddle.to_tensor(bb), paddle.to_tensor(sc), score_threshold=0.1,
        nms_threshold=0.5, background_label=0, normalized=True)
    _, n_pix = vops.multiclass_nms(
        paddle.to_tensor(bb), paddle.to_tensor(sc), score_threshold=0.1,
        nms_threshold=0.5, background_label=0, normalized=False)
    assert int(n_norm.numpy()[0]) == 2
    assert int(n_pix.numpy()[0]) == 1
