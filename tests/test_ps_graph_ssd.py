"""PS graph table + SSD-spill sparse table (VERDICT r4 #7/#8).

Reference models: ``common_graph_table.cc`` (node/edge shards, neighbor
sampling) and ``ssd_sparse_table.cc`` (beyond-memory spill). The
2-process test drives the same server-routed path as the sparse tables.
"""
import os
import socket
import subprocess
import sys
import textwrap

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from paddle_tpu.distributed.ps import (GraphTable, MemorySparseTable,
                                       SsdSparseTable)
from paddle_tpu.distributed.ps.table import AdagradAccessor


# ------------------------------------------------------------- graph local

def test_graph_table_neighbors_and_nodes():
    g = GraphTable(seed=0)
    g.add_edges([1, 1, 1, 2], [10, 11, 12, 20],
                weights=[1.0, 2.0, 3.0, 1.0])
    assert g.size == 6 and g.edge_count() == 4

    nbrs, counts = g.sample_neighbors([1, 2, 99], sample_size=2)
    assert nbrs.shape == (3, 2) and counts.tolist()[1:] == [1, 0]
    assert counts[0] == 2
    assert set(nbrs[0]) <= {10, 11, 12}
    assert nbrs[1, 0] == 20 and nbrs[1, 1] == -1
    assert (nbrs[2] == -1).all()

    # all neighbors returned when k >= degree
    nbrs3, c3 = g.sample_neighbors([1], sample_size=8)
    assert c3[0] == 3 and sorted(nbrs3[0][:3].tolist()) == [10, 11, 12]

    # weighted sampling draws only real neighbors and returns weights
    nw, cw, w = g.sample_neighbors([1], 2, need_weight=True)
    assert set(nw[0]) <= {10, 11, 12} and (w[0] > 0).all()

    nodes = g.sample_nodes(4)
    assert set(nodes.tolist()) <= {1, 2, 10, 11, 12, 20}
    assert g.node_degree([1, 2, 10]).tolist() == [3, 1, 0]


def test_graph_table_features_and_persistence(tmp_path):
    g = GraphTable()
    g.set_node_feat([1, 2], "emb", np.eye(2, 3, dtype=np.float32))
    got = g.get_node_feat([2, 1], "emb")
    np.testing.assert_allclose(got, np.eye(2, 3)[::-1])
    # default fills missing nodes
    d = g.get_node_feat([1, 7], "emb", default=np.zeros(3, np.float32))
    np.testing.assert_allclose(d[1], 0.0)

    g.add_edges([1], [2])
    path = str(tmp_path / "graph.bin")
    g.save(path)
    g2 = GraphTable()
    g2.load(path)
    assert g2.size == g.size and g2.edge_count() == 1
    np.testing.assert_allclose(g2.get_node_feat([1], "emb"),
                               g.get_node_feat([1], "emb"))


def test_graph_table_edge_file(tmp_path):
    p = tmp_path / "edges.txt"
    p.write_text("1 2 0.5\n1 3\n4 1\n")
    g = GraphTable()
    assert g.load_edge_file(str(p)) == 3
    assert g.edge_count() == 3 and g.size == 4
    nbrs, counts = g.sample_neighbors([1], 4)
    assert counts[0] == 2 and set(nbrs[0][:2]) == {2, 3}
    # reverse=True flips the direction
    g2 = GraphTable()
    g2.load_edge_file(str(p), reverse=True)
    nbrs2, c2 = g2.sample_neighbors([2], 4)
    assert c2[0] == 1 and nbrs2[0, 0] == 1


# ------------------------------------------------------------- ssd spill

def test_ssd_table_spills_and_restores(tmp_path):
    t = SsdSparseTable(emb_dim=4, max_mem_rows=4,
                       path=str(tmp_path / "t.ssd"))
    oracle = MemorySparseTable(emb_dim=4)
    # identical init: zero rows
    t._init = oracle._init = lambda: np.zeros(4, np.float32)

    ids = np.arange(20, dtype=np.int64)
    grads = np.outer(np.arange(20), np.ones(4)).astype(np.float32)
    t.push(ids, grads)
    oracle.push(ids, grads)
    assert t.mem_rows <= 4
    assert t.size == 20 and t.disk_rows >= 16
    assert t._spilled > 0

    # rows come back transparently from disk, exact
    np.testing.assert_allclose(t.pull(ids), oracle.pull(ids))
    assert t.mem_rows <= 4  # the sweep re-evicted


def test_ssd_table_accessor_slots_survive_spill(tmp_path):
    """Adagrad g2sum must spill and return with the row, or post-restore
    updates use the wrong learning rate."""
    t = SsdSparseTable(emb_dim=2, accessor=AdagradAccessor(),
                       max_mem_rows=2, path=str(tmp_path / "a.ssd"))
    oracle = MemorySparseTable(emb_dim=2, accessor=AdagradAccessor())
    t._init = oracle._init = lambda: np.zeros(2, np.float32)
    ids = np.arange(8, dtype=np.int64)
    g = np.ones((8, 2), np.float32)
    for _ in range(3):  # repeated pushes force spill/reload cycles
        t.push(ids, g)
        oracle.push(ids, g)
    np.testing.assert_allclose(t.pull(ids), oracle.pull(ids), rtol=1e-6)


def test_ssd_table_save_does_not_mutate_tiers(tmp_path):
    """save() must not spill-then-dump: resident rows would end up in
    BOTH tiers, inflating size on every checkpoint."""
    t = SsdSparseTable(emb_dim=2, max_mem_rows=100,
                       path=str(tmp_path / "nm.ssd"))
    ids = np.arange(10, dtype=np.int64)
    t.push(ids, np.ones((10, 2), np.float32))
    assert t.size == 10 and t.disk_rows == 0
    t.save(str(tmp_path / "ck.npz"))
    assert t.size == 10 and t.disk_rows == 0 and t.mem_rows == 10


def test_ssd_table_save_load_covers_both_tiers(tmp_path):
    t = SsdSparseTable(emb_dim=3, max_mem_rows=2,
                       path=str(tmp_path / "s.ssd"))
    ids = np.arange(6, dtype=np.int64)
    t.push(ids, np.ones((6, 3), np.float32))
    vals = t.pull(ids)
    save_path = str(tmp_path / "ckpt.npz")
    t.save(save_path)

    t2 = SsdSparseTable(emb_dim=3, max_mem_rows=2,
                        path=str(tmp_path / "s2.ssd"))
    t2.load(save_path)
    assert t2.size == 6 and t2.mem_rows <= 2  # residency bound holds
    np.testing.assert_allclose(t2.pull(ids), vals)


# ------------------------------------------------------ 2-process service

def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_graph_service_two_servers(tmp_path):
    server_script = tmp_path / "graph_server.py"
    server_script.write_text(textwrap.dedent("""
        import os, sys
        import jax
        jax.config.update("jax_platforms", "cpu")
        sys.path.insert(0, os.environ["REPO"])
        from paddle_tpu.distributed.ps import service
        rank = int(os.environ["PADDLE_TRAINER_ID"])
        service.run_server(f"ps{rank}")
        print("server-exit-ok", flush=True)
    """))
    port = _free_port()
    world = 3
    env_base = {**os.environ, "JAX_PLATFORMS": "cpu", "REPO": REPO,
                "PADDLE_TRAINERS_NUM": str(world),
                "PADDLE_MASTER_ENDPOINT": f"127.0.0.1:{port}"}
    procs = [subprocess.Popen(
        [sys.executable, str(server_script)],
        env={**env_base, "PADDLE_TRAINER_ID": str(rank)},
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        for rank in range(2)]

    from paddle_tpu.distributed import rpc
    from paddle_tpu.distributed.ps import PsRpcClient
    rpc.init_rpc("trainer0", rank=2, world_size=world,
                 master_endpoint=f"127.0.0.1:{port}")
    try:
        client = PsRpcClient(["ps0", "ps1"])
        client.create_graph_table(7, seed=3)
        # node ids land on BOTH shards (odd/even)
        src = np.array([0, 0, 1, 1, 2, 3], np.int64)
        dst = np.array([1, 2, 2, 3, 0, 0], np.int64)
        client.add_graph_edges(7, src, dst)
        assert client.graph_edge_count(7) == 6
        assert client.table_size(7) == 4

        nbrs, counts = client.sample_neighbors(7, [0, 1, 2, 3, 9], 2)
        assert nbrs.shape == (5, 2)
        assert counts.tolist() == [2, 2, 1, 1, 0]
        assert set(nbrs[0]) == {1, 2} and set(nbrs[1]) == {2, 3}
        assert nbrs[2, 0] == 0 and nbrs[3, 0] == 0

        client.set_node_feat(7, [0, 1, 2, 3], "h",
                             np.arange(8, dtype=np.float32).reshape(4, 2))
        got = client.get_node_feat(7, [3, 0], "h")
        np.testing.assert_allclose(got, [[6, 7], [0, 1]])

        nodes = client.sample_graph_nodes(7, 6)
        assert len(nodes) == 6 and set(nodes.tolist()) <= {0, 1, 2, 3}

        # per-shard persistence round trip
        client.save(7, str(tmp_path / "g"))
        client.load(7, str(tmp_path / "g"))
        assert client.graph_edge_count(7) == 6

    finally:
        # stop servers BEFORE rpc.shutdown (shutdown blocks while peers
        # serve), and never let a failed assertion leave them running
        try:
            client.stop_server()
        except Exception:
            pass
        rpc.shutdown()
        for p in procs:
            try:
                out, _ = p.communicate(timeout=60)
            except subprocess.TimeoutExpired:
                p.kill()
                out, _ = p.communicate()
                raise AssertionError(f"server hung: {out[-2000:]}")
            assert p.returncode == 0, out[-2000:]
            assert "server-exit-ok" in out
