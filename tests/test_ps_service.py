"""Networked PS service: 2 server processes + this process as worker.

Reference test model (SURVEY §4.3): real multiprocess on one host over
loopback, like the brpc PS tests.
"""
import os
import socket
import subprocess
import sys
import textwrap

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_ps_service_end_to_end(tmp_path):
    server_script = tmp_path / "ps_server.py"
    server_script.write_text(textwrap.dedent("""
        import os, sys
        import jax
        jax.config.update("jax_platforms", "cpu")  # survive a wedged chip
        sys.path.insert(0, os.environ["REPO"])
        from paddle_tpu.distributed.ps import service
        rank = int(os.environ["PADDLE_TRAINER_ID"])
        service.run_server(f"ps{rank}")
        print("server-exit-ok", flush=True)
    """))
    port = _free_port()
    world = 3  # 2 servers + this worker
    env_base = {**os.environ, "JAX_PLATFORMS": "cpu", "REPO": REPO,
                "PADDLE_TRAINERS_NUM": str(world),
                "PADDLE_MASTER_ENDPOINT": f"127.0.0.1:{port}"}
    procs = [subprocess.Popen(
        [sys.executable, str(server_script)],
        env={**env_base, "PADDLE_TRAINER_ID": str(rank)},
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        for rank in range(2)]

    from paddle_tpu.distributed import rpc
    from paddle_tpu.distributed.ps import PsRpcClient
    rpc.init_rpc("trainer0", rank=2, world_size=world,
                 master_endpoint=f"127.0.0.1:{port}")
    try:
        client = PsRpcClient(["ps0", "ps1"])
        client.create_sparse_table(0, emb_dim=4, accessor="sgd",
                                   initializer="zeros")
        client.create_dense_table(1, shape=[3], accessor="sgd")

        ids = np.array([0, 1, 2, 3, 10, 11], np.int64)
        rows = client.pull_sparse(0, ids)
        assert rows.shape == (6, 4)
        np.testing.assert_allclose(rows, 0.0)

        # push grads: sgd lr=0.01 default -> rows become -lr*grad
        grads = np.ones((6, 4), np.float32)
        client.push_sparse_grad(0, ids, grads)
        rows2 = client.pull_sparse(0, ids)
        np.testing.assert_allclose(rows2, -0.01, rtol=1e-5)
        # shard routing really splits ids across the two servers
        assert client.table_size(0) == 6
        # 2-D id batches keep their shape
        rows3 = client.pull_sparse(0, ids.reshape(2, 3))
        assert rows3.shape == (2, 3, 4)
        # empty batch: shape-correct (0, dim) result, no crash
        empty = client.pull_sparse(0, np.array([], np.int64))
        assert empty.shape == (0, 4)

        dense = client.pull_dense(1)
        client.push_dense_grad(1, np.ones(3, np.float32))
        np.testing.assert_allclose(client.pull_dense(1), dense - 0.01,
                                   rtol=1e-5)

        # dense tables live only on servers[0]: save/load/table_size must
        # route there instead of fanning out (round-2 advice — a fan-out
        # raised a remote KeyError on ps1)
        client.table_size(1)
        client.save(1, str(tmp_path / "d1"))
        client.push_dense_grad(1, np.ones(3, np.float32))  # diverge
        client.load(1, str(tmp_path / "d1"))
        np.testing.assert_allclose(client.pull_dense(1), dense - 0.01,
                                   rtol=1e-5)

        # save/load shard round trip
        client.save(0, str(tmp_path / "t0"))
        client.push_sparse_grad(0, ids, grads)  # diverge
        client.load(0, str(tmp_path / "t0"))
        np.testing.assert_allclose(client.pull_sparse(0, ids), -0.01,
                                   rtol=1e-5)

    finally:
        # always release the servers first — rpc.shutdown() barriers with
        # them, so a test failure must not leave them waiting forever
        try:
            client.stop_server()
            rpc.shutdown()
        except Exception:
            for p in procs:
                p.kill()
            # peers are dead: a graceful barrier would hang for the full
            # store timeout
            rpc.shutdown(graceful=False)
    for rank, p in enumerate(procs):
        out = p.communicate(timeout=60)[0]
        assert p.returncode == 0, f"ps{rank} failed:\n{out}"
        assert "server-exit-ok" in out
