"""Quantization / inference Predictor / static control-flow tests.

Parity model: reference quantization tests (QAT improves-or-holds accuracy,
convert bakes quantized weights), inference API tests (save → Config →
create_predictor → handles round trip), and control_flow tests (while_loop /
cond numeric contracts, dygraph == compiled).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, ops, optimizer as opt
from paddle_tpu.quantization import (
    QAT, PTQ, QuantConfig, QuanterFactory, FakeQuanterWithAbsMaxObserver,
    fake_quant_dequant_abs_max,
)
from paddle_tpu.quantization.qat import QuantedWrapper
from paddle_tpu.static.nn import while_loop, cond, switch_case


def _np(t):
    return np.asarray(t._value)


# -------------------------------------------------------------- quant
def test_fake_quant_dequant_roundtrip_and_ste():
    x = paddle.to_tensor(np.linspace(-1, 1, 11).astype(np.float32))
    x.stop_gradient = False
    y = fake_quant_dequant_abs_max(x, bit_length=8)
    # 8-bit grid error bound: scale/127
    assert np.abs(_np(y) - _np(x)).max() <= 1.0 / 127 + 1e-6
    ops.sum(y).backward()
    np.testing.assert_allclose(_np(x.grad), np.ones(11), rtol=1e-6)  # STE


def test_qat_quantize_convert():
    paddle.seed(0)
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
    x = paddle.to_tensor(
        np.random.default_rng(0).standard_normal((4, 8)).astype(np.float32))
    ref = _np(net(x))

    qat = QAT()
    net = qat.quantize(net)  # inplace=False returns the quantized copy
    assert isinstance(net._sub_layers["0"], QuantedWrapper)
    out_q = _np(net(x))
    # fake-quant output is close to fp but not identical
    assert np.abs(out_q - ref).max() < 0.2
    # trains through the quantizers (STE)
    y = paddle.to_tensor(np.zeros((4, 4), np.float32))
    o = opt.SGD(learning_rate=0.01, parameters=net.parameters())
    loss = ops.mean((net(x) - y) ** 2)
    loss.backward()
    o.step()
    # convert: wrappers replaced, weights baked, activation scales frozen
    net = qat.convert(net, inplace=True)
    from paddle_tpu.quantization.qat import ConvertedLayer
    assert isinstance(net._sub_layers["0"], (nn.Linear, ConvertedLayer))
    assert np.isfinite(_np(net(x))).all()


def test_qat_respects_type_config():
    cfg = QuantConfig()
    cfg.add_type_config(
        nn.Linear,
        activation=QuanterFactory(FakeQuanterWithAbsMaxObserver),
        weight=QuanterFactory(FakeQuanterWithAbsMaxObserver))
    net = nn.Sequential(nn.Linear(4, 4), nn.Conv2D(1, 1, 3))
    q = QAT(cfg).quantize(net)
    assert isinstance(q._sub_layers["0"], QuantedWrapper)
    assert isinstance(q._sub_layers["1"], nn.Conv2D)  # not configured
    assert isinstance(net._sub_layers["0"], nn.Linear)  # original untouched


def test_ptq_observe_convert():
    paddle.seed(1)
    net = nn.Sequential(nn.Linear(8, 8), nn.ReLU(), nn.Linear(8, 2))
    ptq = PTQ()
    net = ptq.quantize(net)
    rng = np.random.default_rng(1)
    for _ in range(4):  # calibration
        net(paddle.to_tensor(rng.standard_normal((8, 8)).astype(np.float32)))
    w_before = _np(net._sub_layers["0"].inner.weight).copy()
    net = ptq.convert(net, inplace=True)
    from paddle_tpu.quantization.qat import ConvertedLayer
    first = net._sub_layers["0"]
    assert isinstance(first, (nn.Linear, ConvertedLayer))
    w_after = _np(first.weight if isinstance(first, nn.Linear)
                  else first.inner.weight)
    assert not np.allclose(w_before, w_after)       # quantized grid
    assert np.abs(w_before - w_after).max() < 0.05  # but close


# ---------------------------------------------------------- inference
def test_predictor_roundtrip(tmp_path):
    from paddle_tpu.inference import Config, create_predictor
    paddle.seed(2)
    net = nn.Sequential(nn.Linear(8, 16), nn.Tanh(), nn.Linear(16, 3))
    x = np.random.default_rng(2).standard_normal((5, 8)).astype(np.float32)
    want = _np(net(paddle.to_tensor(x)))

    path = str(tmp_path / "model")
    paddle.jit.save(net, path,
                    input_spec=[paddle.jit.InputSpec([5, 8], "float32")])

    config = Config(path)
    pred = create_predictor(config)
    # direct run
    (out,) = pred.run([x])
    np.testing.assert_allclose(out, want, rtol=1e-5, atol=1e-5)
    # handle protocol
    names = pred.get_input_names()
    h = pred.get_input_handle(names[0])
    h.copy_from_cpu(x)
    pred.run()
    out2 = pred.get_output_handle(pred.get_output_names()[0]).copy_to_cpu()
    np.testing.assert_allclose(out2, want, rtol=1e-5, atol=1e-5)


def test_predictor_missing_model():
    from paddle_tpu.inference import Config, create_predictor
    with pytest.raises(ValueError):
        create_predictor(Config("/nonexistent/model"))


# -------------------------------------------------------- control flow
def test_while_loop_eager():
    i = paddle.to_tensor(np.int64(0))
    s = paddle.to_tensor(np.float32(0))
    i2, s2 = while_loop(lambda i, s: i < 5,
                        lambda i, s: [i + 1, s + ops.cast(i, "float32")],
                        [i, s])
    assert int(_np(i2)) == 5 and float(_np(s2)) == 10.0


def test_while_loop_compiled():
    @paddle.jit.to_static
    def count_to(n):
        i = paddle.to_tensor(np.int64(0))
        tot = paddle.to_tensor(np.float32(0))
        i, tot = while_loop(lambda i, t: i < n,
                            lambda i, t: [i + 1, t + 2.0], [i, tot])
        return tot

    out = count_to(paddle.to_tensor(np.int64(7)))
    assert float(_np(out)) == 14.0
    out2 = count_to(paddle.to_tensor(np.int64(3)))  # same trace, new bound
    assert float(_np(out2)) == 6.0


def test_cond_eager_and_compiled_grad():
    x = paddle.to_tensor(np.float32(2.0))
    out = cond(x > 1.0, lambda: x * 2, lambda: x * 3)
    assert float(_np(out)) == 4.0

    @paddle.jit.to_static
    def f(x):
        return cond(x > 0, lambda: x * 2.0, lambda: x * -1.0)

    xp = paddle.to_tensor(np.float32(3.0))
    xp.stop_gradient = False
    y = f(xp)
    assert float(_np(y)) == 6.0
    y.backward()
    assert float(_np(xp.grad)) == 2.0  # grad flows through lax.cond
    xn = paddle.to_tensor(np.float32(-3.0))
    assert float(_np(f(xn))) == 3.0


def test_switch_case():
    fns = {1: lambda: paddle.to_tensor(np.float32(10)),
           3: lambda: paddle.to_tensor(np.float32(30))}
    out = switch_case(paddle.to_tensor(np.int64(3)), fns,
                      default=lambda: paddle.to_tensor(np.float32(-1)))
    assert float(_np(out)) == 30.0
    out = switch_case(paddle.to_tensor(np.int64(7)), fns,
                      default=lambda: paddle.to_tensor(np.float32(-1)))
    assert float(_np(out)) == -1.0


def test_predictor_batch_bucketing_and_clone(tmp_path):
    """Serving depth: one fixed-shape exported program serves any batch
    (pad/chunk + slice), clone() shares weights, outputs stay device-
    resident until copy_to_cpu (AnalysisPredictor parity)."""
    from paddle_tpu import inference, jit
    from paddle_tpu.jit.save_load import InputSpec

    net = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    path = str(tmp_path / "bucket_model")
    jit.save(net, path, input_spec=[InputSpec([4, 4], "float32")])

    cfg = inference.Config(path)
    pred = inference.create_predictor(cfg)
    rng = np.random.default_rng(0)

    full = rng.standard_normal((4, 4)).astype(np.float32)
    want = net(paddle.to_tensor(full)).numpy()

    # smaller batch than exported: padded + sliced
    out_small = pred.run([full[:2]])[0]
    np.testing.assert_allclose(out_small, want[:2], rtol=1e-5, atol=1e-5)
    # larger, non-multiple batch: chunked + remainder padded
    big = rng.standard_normal((10, 4)).astype(np.float32)
    out_big = pred.run([big])[0]
    want_big = net(paddle.to_tensor(big)).numpy()
    np.testing.assert_allclose(out_big, want_big, rtol=1e-5, atol=1e-5)

    # clone shares program + weights; handle protocol end-to-end
    c = pred.clone()
    h = c.get_input_handle("input_0")
    h.copy_from_cpu(full)
    c.run()
    got = c.get_output_handle("output_0").copy_to_cpu()
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_ptq_observer_family():
    """Observer variety (reference observers/): hist-percentile and KL
    reject outliers that wreck absmax; per-channel gives one scale per
    output channel; moving-average EMA converges to the batch absmax."""
    from paddle_tpu import quantization as Q
    rng = np.random.default_rng(0)

    # activations ~N(0,1) with one 100.0 outlier
    data = rng.standard_normal((64, 32)).astype(np.float32)
    data[0, 0] = 100.0
    absmax = Q.AbsmaxObserver()
    hist = Q.HistObserver(percent=0.999)
    kl = Q.KLObserver()
    ema = Q.MovingAverageAbsmaxObserver(moving_rate=0.5)
    for obs in (absmax, hist, kl, ema):
        for i in range(4):
            obs(paddle.to_tensor(data))
    s_absmax = float(absmax.scales().numpy())
    s_hist = float(hist.scales().numpy())
    s_kl = float(kl.scales().numpy())
    assert s_absmax == pytest.approx(100.0)
    # robust observers clip far below the outlier, above the bulk
    assert 2.0 < s_hist < 50.0, s_hist
    assert 2.0 < s_kl < 50.0, s_kl
    assert float(ema.scales().numpy()) == pytest.approx(100.0, rel=0.2)

    # per-channel: axis-0 scales match each row's absmax
    w = rng.standard_normal((4, 16)).astype(np.float32) * \
        np.array([[1.0], [2.0], [3.0], [4.0]], np.float32)
    pc = Q.PerChannelAbsmaxObserver(quant_axis=0)
    pc(paddle.to_tensor(w))
    np.testing.assert_allclose(pc.scales().numpy(),
                               np.abs(w).max(axis=1), rtol=1e-6)
    assert pc.quant_axis() == 0


def test_ptq_with_hist_observer_end_to_end():
    from paddle_tpu import quantization as Q
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
    cfg = Q.QuantConfig(activation=Q.QuanterFactory(Q.HistObserver),
                        weight=Q.QuanterFactory(Q.AbsmaxObserver))
    ptq = Q.PTQ(cfg)
    m = ptq.quantize(net, inplace=False)
    rng = np.random.default_rng(2)
    for _ in range(4):
        m(paddle.to_tensor(rng.standard_normal((4, 8)).astype(np.float32)))
    q = ptq.convert(m, inplace=True)
    x = paddle.to_tensor(rng.standard_normal((4, 8)).astype(np.float32))
    ref = net(x).numpy()
    got = q(x).numpy()
    # int8 fake-quant stays close to the fp reference
    assert np.abs(got - ref).max() < 0.25 * np.abs(ref).max()


def test_ptq_per_channel_weight_flows_through_convert():
    """PerChannelAbsmaxObserver as the weight quanter actually drives
    per-channel fake quant in convert (scales + quant_axis consulted)."""
    from paddle_tpu import quantization as Q
    from functools import partial
    lin = nn.Linear(8, 4)
    # weight rows scaled very differently: per-tensor absmax would crush
    # the small channels to ~zero resolution
    w = np.ones((8, 4), np.float32) * 0.01
    w[:, 0] = 100.0
    lin.weight.set_value(w)
    net = nn.Sequential(lin)
    cfg = Q.QuantConfig(
        activation=Q.QuanterFactory(Q.AbsmaxObserver),
        weight=Q.QuanterFactory(Q.PerChannelAbsmaxObserver, quant_axis=-1))
    ptq = Q.PTQ(cfg)
    m = ptq.quantize(net, inplace=False)
    rng = np.random.default_rng(3)
    m(paddle.to_tensor(rng.standard_normal((4, 8)).astype(np.float32)))
    q = ptq.convert(m, inplace=True)
    wq = None
    for sub in q._sub_layers.values():
        inner = getattr(sub, "inner", sub)
        if hasattr(inner, "weight"):
            wq = inner.weight.numpy()
    # per-channel: the 0.01 channels survive quantization almost exactly
    np.testing.assert_allclose(wq[:, 1], 0.01, rtol=0.02)
    # negative quant_axis resolved (scales per OUTPUT channel, len 4)


def test_predictor_non_batched_extra_input(tmp_path):
    """Bucketing leaves non-batched inputs (dim0 != exported batch)
    untouched."""
    from paddle_tpu import inference, jit
    from paddle_tpu.jit.save_load import InputSpec

    class Net(nn.Layer):
        def __init__(self):
            super().__init__()
            self.lin = nn.Linear(4, 4)

        def forward(self, x, bias_vec):
            return self.lin(x) + bias_vec

    net = Net()
    path = str(tmp_path / "nb_model")
    jit.save(net, path, input_spec=[InputSpec([8, 4], "float32"),
                                    InputSpec([4], "float32")])
    pred = inference.create_predictor(inference.Config(path))
    rng = np.random.default_rng(1)
    x = rng.standard_normal((3, 4)).astype(np.float32)  # < exported 8
    bias = rng.standard_normal((4,)).astype(np.float32)
    got = pred.run([x, bias])[0]
    want = net(paddle.to_tensor(x), paddle.to_tensor(bias)).numpy()
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_hapi_accumulation_stays_eager():
    """update=False disables the compiled parallel path for the run
    (the compiled step cannot consume accumulated eager grads)."""
    from paddle_tpu.distributed.mesh import HybridCommunicateGroup
    from paddle_tpu.distributed import mesh as mesh_mod
    saved = (mesh_mod._global_mesh, mesh_mod._hcg)
    try:
        mesh_mod._global_mesh, mesh_mod._hcg = None, None
        HybridCommunicateGroup(dp_degree=8)
        net = nn.Linear(4, 1)
        import paddle_tpu.optimizer as opt
        model = paddle.Model(net)
        model.prepare(opt.SGD(learning_rate=0.1,
                              parameters=net.parameters()), nn.MSELoss())
        x = np.ones((8, 4), np.float32)
        y = np.ones((8, 1), np.float32)
        model.train_batch([x], [y], update=False)   # accumulate
        model.train_batch([x], [y], update=True)    # must stay eager
        assert model._parallel_step is None
        assert model._no_parallel
    finally:
        mesh_mod._global_mesh, mesh_mod._hcg = saved


# --------------------------------------------------- int8 deploy path
# (VERDICT r4 #4: save_quantized_model -> jit.save -> Predictor;
#  reference quantization/imperative/qat.py:293, ptq.py:112)

def test_save_quantized_model_roundtrip(tmp_path):
    """QAT model exports as an int8 artifact; Predictor serves it with
    near-fp32 accuracy and the weights really store as int8."""
    import pickle
    from paddle_tpu import inference
    from paddle_tpu.jit.save_load import InputSpec
    from paddle_tpu.quantization import QAT, save_quantized_model

    paddle.seed(11)
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 3))
    rng = np.random.default_rng(4)
    x = rng.standard_normal((6, 8)).astype(np.float32)
    fp32_out = _np(net(paddle.to_tensor(x)))

    qat = QAT()
    qmodel = qat.quantize(net)
    for _ in range(4):  # run calibration batches through the observers
        qmodel(paddle.to_tensor(
            rng.standard_normal((6, 8)).astype(np.float32)))

    path = str(tmp_path / "int8_model")
    deploy = save_quantized_model(
        qmodel, path, input_spec=[InputSpec([6, 8], "float32")])

    # the deploy form really stores int8 weights + scales
    from paddle_tpu.quantization import Int8DeployLayer
    int8_layers = [l for l in deploy.sublayers()
                   if isinstance(l, Int8DeployLayer)]
    assert len(int8_layers) == 2
    assert np.asarray(int8_layers[0].q_weight._value).dtype == np.int8

    # ...and the artifact blob holds int8 (4x smaller than f32)
    with open(path + ".pdiparams", "rb") as f:
        blob = pickle.load(f)

    def _leaf_dtypes(o):
        if isinstance(o, dict):
            for v in o.values():
                yield from _leaf_dtypes(v)
        elif hasattr(o, "array"):  # framework/io.py _TensorPayload
            yield np.asarray(o.array).dtype
    leaf_dtypes = set(_leaf_dtypes(blob))
    assert np.dtype(np.int8) in leaf_dtypes, leaf_dtypes

    pred = inference.create_predictor(inference.Config(path))
    (got,) = pred.run([x])
    # int8 per-channel weight quant + frozen act scales: close to fp32
    err = np.abs(got - fp32_out).max() / (np.abs(fp32_out).max() + 1e-9)
    assert err < 0.1, f"relative error {err}"

    # jit.load also serves the artifact (TranslatedLayer path)
    loaded = paddle.jit.load(path)
    got2 = _np(loaded(paddle.to_tensor(x)))
    np.testing.assert_allclose(got2, got, rtol=1e-5, atol=1e-6)


def test_save_quantized_model_after_convert(tmp_path):
    """convert()ed models (observer-stripped) export too — the PTQ flow."""
    from paddle_tpu import inference
    from paddle_tpu.jit.save_load import InputSpec
    from paddle_tpu.quantization import PTQ, save_quantized_model

    paddle.seed(12)
    net = nn.Sequential(nn.Linear(4, 4), nn.Tanh(), nn.Linear(4, 2))
    rng = np.random.default_rng(5)
    x = rng.standard_normal((3, 4)).astype(np.float32)
    fp32_out = _np(net(paddle.to_tensor(x)))

    ptq = PTQ()
    qmodel = ptq.quantize(net)
    qmodel(paddle.to_tensor(x))  # calibrate
    converted = ptq.convert(qmodel)

    path = str(tmp_path / "ptq_int8")
    save_quantized_model(converted, path,
                         input_spec=[InputSpec([3, 4], "float32")])
    pred = inference.create_predictor(inference.Config(path))
    (got,) = pred.run([x])
    err = np.abs(got - fp32_out).max() / (np.abs(fp32_out).max() + 1e-9)
    assert err < 0.12, f"relative error {err}"
