"""Ring attention / sequence parallel tests.

Oracle: the single-device fused sdpa (_sdpa_ref) over the full sequence —
the ring result must be EXACT attention, forward and backward, causal and
not, on the 8-way sep mesh.
"""
import numpy as np
import pytest
import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu import ops
from paddle_tpu.distributed import mesh as mesh_mod
from paddle_tpu.distributed.mesh import HybridCommunicateGroup
from paddle_tpu.distributed.fleet import ring_attention, split_sequence
from paddle_tpu.nn.functional.attention import (
    scaled_dot_product_attention, _sdpa_ref,
)


@pytest.fixture(autouse=True)
def reset_mesh():
    saved = (mesh_mod._global_mesh, mesh_mod._hcg)
    yield
    mesh_mod._global_mesh, mesh_mod._hcg = saved


def _qkv(B=2, S=32, H=2, D=8, seed=0):
    rng = np.random.default_rng(seed)
    mk = lambda: rng.standard_normal((B, S, H, D)).astype(np.float32)
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [False, True])
def test_ring_matches_full_attention(causal):
    q, k, v = _qkv()
    want = np.asarray(_sdpa_ref(jnp.asarray(q), jnp.asarray(k),
                                jnp.asarray(v), None, 0.0, causal, None,
                                False))
    HybridCommunicateGroup(dp_degree=1, sep_degree=8)
    got = ring_attention(paddle.to_tensor(q), paddle.to_tensor(k),
                         paddle.to_tensor(v), is_causal=causal)
    np.testing.assert_allclose(np.asarray(got._value), want,
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_gradients_match(causal):
    q, k, v = _qkv(S=16)

    def run(path):
        tq, tk, tv = (paddle.to_tensor(q), paddle.to_tensor(k),
                      paddle.to_tensor(v))
        for t in (tq, tk, tv):
            t.stop_gradient = False
        if path == "ring":
            out = ring_attention(tq, tk, tv, is_causal=causal)
        else:
            out = scaled_dot_product_attention(tq, tk, tv, is_causal=causal)
        w = paddle.to_tensor(
            np.cos(np.arange(out._value.size, dtype=np.float32))
            .reshape(out.shape))
        ops.sum(out * w).backward()
        return (np.asarray(tq.grad._value), np.asarray(tk.grad._value),
                np.asarray(tv.grad._value))

    ref = run("full")  # no mesh: sdpa oracle
    HybridCommunicateGroup(dp_degree=1, sep_degree=8)
    got = run("ring")
    for g, r in zip(got, ref):
        np.testing.assert_allclose(g, r, rtol=5e-5, atol=5e-5)


def test_ring_degenerate_fallback():
    """No sep axis active -> plain sdpa (identical values)."""
    q, k, v = _qkv(S=16)
    out1 = ring_attention(paddle.to_tensor(q), paddle.to_tensor(k),
                          paddle.to_tensor(v), is_causal=True)
    out2 = scaled_dot_product_attention(
        paddle.to_tensor(q), paddle.to_tensor(k), paddle.to_tensor(v),
        is_causal=True)
    np.testing.assert_allclose(np.asarray(out1._value),
                               np.asarray(out2._value), rtol=1e-6)


def test_ring_compiles_with_collective_permute():
    """The compiled module must move K/V via collective-permute (ICI hops)."""
    from paddle_tpu.kernels.ring_attention import ring_attention_sharded
    q, k, v = _qkv()
    hcg = HybridCommunicateGroup(dp_degree=1, sep_degree=8)
    txt = jax.jit(
        lambda a, b, c: ring_attention_sharded(
            a, b, c, hcg.mesh, "sep", causal=True)
    ).lower(q, k, v).compile().as_text()
    assert "collective-permute" in txt


def test_split_sequence_shards_activation():
    hcg = HybridCommunicateGroup(dp_degree=1, sep_degree=8)
    x = paddle.to_tensor(np.zeros((2, 32, 8), np.float32))

    @paddle.jit.to_static
    def f(t):
        return split_sequence(t) * 2.0

    out = f(x)
    assert tuple(out.shape) == (2, 32, 8)


def test_long_sequence_runs():
    """S=1024 over sep=8: per-device logits are 128x1024... ring keeps it
    at [B,H,128,128] per step; just assert it runs and is finite."""
    q, k, v = _qkv(B=1, S=1024, H=2, D=16, seed=3)
    HybridCommunicateGroup(dp_degree=1, sep_degree=8)
    out = ring_attention(paddle.to_tensor(q), paddle.to_tensor(k),
                         paddle.to_tensor(v), is_causal=True)
    assert np.isfinite(np.asarray(out._value)).all()
