"""paddle.distributed.rpc tests.

Reference test model: real multi-process on one host, loopback only
(SURVEY §4.3 / unittests/rpc). Single-process world=1 covers the agent
round-trip; the 2-process test exercises the TCPStore rendezvous +
cross-process calls exactly like the reference's test_rpc suite.
"""
import os
import socket
import subprocess
import sys
import textwrap

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _add(a, b):
    return a + b


def _boom():
    raise ValueError("remote kaboom")


def _unpicklable_reply():
    return lambda x: x  # local lambdas don't pickle


@pytest.fixture
def rpc_world1():
    from paddle_tpu.distributed import rpc
    rpc.init_rpc("worker0", rank=0, world_size=1,
                 master_endpoint=f"127.0.0.1:{_free_port()}")
    yield rpc
    rpc.shutdown()


def test_rpc_sync_async_self(rpc_world1):
    rpc = rpc_world1
    assert rpc.rpc_sync("worker0", _add, args=(2, 3)) == 5
    fut = rpc.rpc_async("worker0", _add, args=(10,), kwargs={"b": 4})
    assert fut.wait() == 14
    # numpy payloads pickle through
    out = rpc.rpc_sync("worker0", np.square, args=(np.arange(4.0),))
    np.testing.assert_allclose(out, [0, 1, 4, 9])


def test_rpc_remote_exception_and_infos(rpc_world1):
    rpc = rpc_world1
    with pytest.raises(RuntimeError, match="remote kaboom"):
        rpc.rpc_sync("worker0", _boom)
    me = rpc.get_current_worker_info()
    assert me.name == "worker0" and me.rank == 0
    assert rpc.get_worker_info("worker0") == me
    assert rpc.get_all_worker_infos() == [me]
    with pytest.raises(ValueError):
        rpc.rpc_sync("nosuch", _add, args=(1, 2))


def test_rpc_unpicklable_reply_is_diagnosable(rpc_world1):
    """A result that fails to pickle must surface the serialization error
    to the caller, not kill the handler thread/connection (round-2
    advice)."""
    rpc = rpc_world1
    with pytest.raises(RuntimeError,
                       match="reply could not be serialized"):
        rpc.rpc_sync("worker0", _unpicklable_reply)
    # and the connection stays usable afterwards
    assert rpc.rpc_sync("worker0", _add, args=(1, 2)) == 3


def test_rpc_two_processes(tmp_path):
    script = tmp_path / "rpc_child.py"
    script.write_text(textwrap.dedent("""
        import os, sys
        import jax
        jax.config.update("jax_platforms", "cpu")  # survive a wedged chip
        sys.path.insert(0, os.environ["REPO"])
        from paddle_tpu.distributed import rpc

        rank = int(os.environ["PADDLE_TRAINER_ID"])
        rpc.init_rpc(f"worker{rank}")
        peer = f"worker{1 - rank}"
        # each rank asks its peer to evaluate rank-dependent math
        out = rpc.rpc_sync(peer, pow, args=(2, 5 + rank))
        assert out == 2 ** (5 + rank), out
        fut = rpc.rpc_async(peer, len, args=("abcd",))
        assert fut.wait() == 4
        infos = rpc.get_all_worker_infos()
        assert [i.name for i in infos] == ["worker0", "worker1"]
        rpc.shutdown()
        print(f"rpc-ok-{rank}", flush=True)
    """))
    port = _free_port()
    procs = []
    for rank in range(2):
        env = {**os.environ,
               "JAX_PLATFORMS": "cpu",
               "REPO": REPO,
               "PADDLE_TRAINER_ID": str(rank),
               "PADDLE_TRAINERS_NUM": "2",
               "PADDLE_MASTER_ENDPOINT": f"127.0.0.1:{port}"}
        procs.append(subprocess.Popen(
            [sys.executable, str(script)], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True))
    outs = [p.communicate(timeout=120)[0] for p in procs]
    for rank, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank{rank} failed:\n{out}"
        assert f"rpc-ok-{rank}" in out
