"""Launcher / PyLayer / nan-inf / eager collectives / mp DataLoader tests.

Parity model: reference launcher tests run real ``python -m ...launch``
subprocesses (test_communication_api_base.py:39-49); PyLayer tests are
autograd-oracle checks (test_pylayer_op.py); nan_inf mirrors
test_nan_inf_utils; DataLoader worker tests mirror
test_multiprocess_dataloader_static.py.
"""
import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, ops
from paddle_tpu.autograd import PyLayer
from paddle_tpu.io import Dataset, DataLoader

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# --------------------------------------------------------------- PyLayer
def test_pylayer_matches_autograd():
    class Cube(PyLayer):
        @staticmethod
        def forward(ctx, x):
            ctx.save_for_backward(x)
            return x * x * x

        @staticmethod
        def backward(ctx, grad):
            (x,) = ctx.saved_tensor()
            return grad * 3.0 * x * x

    x = paddle.to_tensor(np.array([1.0, -2.0, 3.0], np.float32))
    x.stop_gradient = False
    y = Cube.apply(x)
    ops.sum(y).backward()
    np.testing.assert_allclose(np.asarray(x.grad._value),
                               3.0 * np.array([1.0, 4.0, 9.0]), rtol=1e-6)


def test_pylayer_multi_io_and_chaining():
    class MulAdd(PyLayer):
        @staticmethod
        def forward(ctx, a, b):
            ctx.save_for_backward(a, b)
            return a * b, a + b

        @staticmethod
        def backward(ctx, g_mul, g_add):
            a, b = ctx.saved_tensor()
            return g_mul * b + g_add, g_mul * a + g_add

    a = paddle.to_tensor(np.array([2.0], np.float32))
    b = paddle.to_tensor(np.array([5.0], np.float32))
    a.stop_gradient = b.stop_gradient = False
    m, s = MulAdd.apply(a, b)
    # chain into taped ops after the PyLayer
    loss = ops.sum(m * s)
    loss.backward()
    # d/da [ab(a+b)] = 2ab + b^2 = 20+25 ; d/db = a^2 + 2ab = 4+20
    np.testing.assert_allclose(float(a.grad._value[0]), 45.0, rtol=1e-6)
    np.testing.assert_allclose(float(b.grad._value[0]), 24.0, rtol=1e-6)


def test_pylayer_apply_not_overridable():
    with pytest.raises(TypeError):
        class Bad(PyLayer):
            @staticmethod
            def apply(*a):
                pass


# --------------------------------------------------------------- nan/inf
def test_check_nan_inf_flag():
    x = paddle.to_tensor(np.array([1.0, 0.0], np.float32))
    paddle.set_flags({"FLAGS_check_nan_inf": True})
    try:
        with pytest.raises(FloatingPointError):
            _ = x / paddle.to_tensor(np.array([1.0, 0.0], np.float32))
        # warn-only level
        paddle.set_flags({"FLAGS_check_nan_inf_level": 1})
        with pytest.warns(UserWarning):
            _ = x / paddle.to_tensor(np.array([1.0, 0.0], np.float32))
    finally:
        paddle.set_flags({"FLAGS_check_nan_inf": False,
                          "FLAGS_check_nan_inf_level": 0})


# --------------------------------------------------- eager collectives
def test_broadcast_sharded_real():
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P
    from paddle_tpu.distributed import mesh as mesh_mod
    from paddle_tpu.distributed.mesh import build_mesh, set_global_mesh, Group
    import paddle_tpu.distributed as dist

    saved = (mesh_mod._global_mesh, mesh_mod._hcg)
    try:
        mesh = build_mesh(dp=8)
        set_global_mesh(mesh)
        g = Group("dp", mesh)
        data = np.arange(16, dtype=np.float32).reshape(8, 2)
        arr = jax.device_put(data, NamedSharding(mesh, P("dp", None)))
        t = paddle.Tensor(arr)
        dist.broadcast(t, src=3, group=g)
        got = np.asarray(t._value)
        want = np.tile(data[3], (8, 1))
        np.testing.assert_allclose(got, want)
    finally:
        mesh_mod._global_mesh, mesh_mod._hcg = saved


def test_all_gather_sharded_real():
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P
    from paddle_tpu.distributed import mesh as mesh_mod
    from paddle_tpu.distributed.mesh import build_mesh, set_global_mesh, Group
    import paddle_tpu.distributed as dist

    saved = (mesh_mod._global_mesh, mesh_mod._hcg)
    try:
        mesh = build_mesh(dp=8)
        set_global_mesh(mesh)
        g = Group("dp", mesh)
        data = np.arange(8, dtype=np.float32).reshape(8, 1)
        arr = jax.device_put(data, NamedSharding(mesh, P("dp", None)))
        out = []
        dist.all_gather(out, paddle.Tensor(arr), group=g)
        assert len(out) == 8
        for i in range(8):
            np.testing.assert_allclose(np.asarray(out[i]._value),
                                       data[i:i + 1])
    finally:
        mesh_mod._global_mesh, mesh_mod._hcg = saved


def test_all_to_all_places_chunks():
    from paddle_tpu.distributed import mesh as mesh_mod
    from paddle_tpu.distributed.mesh import build_mesh, set_global_mesh, Group
    import paddle_tpu.distributed as dist

    saved = (mesh_mod._global_mesh, mesh_mod._hcg)
    try:
        mesh = build_mesh(dp=8)
        set_global_mesh(mesh)
        g = Group("dp", mesh)
        ins = [paddle.to_tensor(np.full((2,), i, np.float32))
               for i in range(8)]
        outs = []
        dist.all_to_all(outs, ins, group=g)
        assert len(outs) == 8
        for j, o in enumerate(outs):
            np.testing.assert_allclose(np.asarray(o._value), np.full((2,), j))
            # every chunk is readable from every group device (replicated)
            assert len(o._value.devices()) == 8
            # and outputs stay composable with each other
            _ = outs[0] + outs[j]
    finally:
        mesh_mod._global_mesh, mesh_mod._hcg = saved


def test_all_gather_foreign_axis_resharded():
    """Input sharded over mp, gathered over dp: must yield full tensors."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P
    from paddle_tpu.distributed import mesh as mesh_mod
    from paddle_tpu.distributed.mesh import build_mesh, set_global_mesh, Group
    import paddle_tpu.distributed as dist

    saved = (mesh_mod._global_mesh, mesh_mod._hcg)
    try:
        mesh = build_mesh(dp=2, mp=2)
        set_global_mesh(mesh)
        g = Group("dp", mesh)
        data = np.arange(16, dtype=np.float32).reshape(4, 4)
        arr = jax.device_put(data, NamedSharding(mesh, P(None, "mp")))
        out = []
        dist.all_gather(out, paddle.Tensor(arr), group=g)
        assert len(out) == 2
        for o in out:  # replicated input w.r.t. dp ⇒ each rank holds it all
            np.testing.assert_allclose(np.asarray(o._value), data)
    finally:
        mesh_mod._global_mesh, mesh_mod._hcg = saved


# ------------------------------------------------------------- launcher
def test_launcher_spawns_env_contract(tmp_path):
    script = tmp_path / "probe.py"
    script.write_text(textwrap.dedent("""
        import os, sys
        rank = os.environ["PADDLE_TRAINER_ID"]
        n = os.environ["PADDLE_TRAINERS_NUM"]
        eps = os.environ["PADDLE_TRAINER_ENDPOINTS"]
        cur = os.environ["PADDLE_CURRENT_ENDPOINT"]
        assert cur in eps.split(","), (cur, eps)
        print(f"rank={rank} n={n}", flush=True)
    """))
    log_dir = str(tmp_path / "logs")
    rc = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "2", "--log_dir", log_dir, str(script)],
        cwd=REPO, env={**os.environ, "JAX_PLATFORMS": "cpu"},
        capture_output=True, text=True, timeout=120)
    assert rc.returncode == 0, rc.stderr
    logs = sorted(os.listdir(log_dir))
    assert logs == ["workerlog.0", "workerlog.1"]
    body = open(os.path.join(log_dir, "workerlog.0")).read() + \
        open(os.path.join(log_dir, "workerlog.1")).read()
    assert "rank=0 n=2" in body and "rank=1 n=2" in body


def test_launcher_propagates_failure(tmp_path):
    script = tmp_path / "boom.py"
    script.write_text("import sys; sys.exit(3)\n")
    rc = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "2", str(script)],
        cwd=REPO, env={**os.environ, "JAX_PLATFORMS": "cpu"},
        capture_output=True, text=True, timeout=120)
    assert rc.returncode == 3


# ------------------------------------------------------ mp DataLoader
class _SquareDS(Dataset):
    def __init__(self, n=64):
        self.n = n

    def __getitem__(self, i):
        return np.full((3,), i, np.float32), np.int64(i)

    def __len__(self):
        return self.n


def test_mp_dataloader_matches_sync():
    ds = _SquareDS(40)
    sync = [b for b in DataLoader(ds, batch_size=8, num_workers=0)]
    mp = [b for b in DataLoader(ds, batch_size=8, num_workers=3)]
    assert len(sync) == len(mp) == 5
    for (sx, sy), (mx, my) in zip(sync, mp):
        np.testing.assert_allclose(np.asarray(sx._value),
                                   np.asarray(mx._value))
        np.testing.assert_allclose(np.asarray(sy._value),
                                   np.asarray(my._value))


def test_mp_dataloader_propagates_worker_error():
    class Bad(Dataset):
        def __getitem__(self, i):
            if i == 5:
                raise ValueError("bad sample")
            return np.zeros(2, np.float32)

        def __len__(self):
            return 8

    with pytest.raises(ValueError, match="bad sample"):
        list(DataLoader(Bad(), batch_size=2, num_workers=2))


def test_mp_dataloader_worker_init_fn():
    ds = _SquareDS(8)
    loader = DataLoader(ds, batch_size=4, num_workers=2,
                        worker_init_fn=lambda wid: None)
    assert len(list(loader)) == 2


def test_pylayer_multi_output_backward():
    """Multi-output PyLayer: backward receives one cotangent per output
    (regression: TapeNode.multi_out must be set for PyLayer nodes)."""
    class TwoOut(PyLayer):
        @staticmethod
        def forward(ctx, x):
            ctx.save_for_backward(x)
            return x * 2.0, x * x

        @staticmethod
        def backward(ctx, ga, gb):
            (x,) = ctx.saved_tensor()
            return ga * 2.0 + gb * 2.0 * x

    x = paddle.to_tensor(np.array([1.0, -2.0], np.float32))
    x.stop_gradient = False
    a, b = TwoOut.apply(x)
    (ops.sum(a) + ops.sum(b)).backward()
    np.testing.assert_allclose(np.asarray(x.grad._value),
                               2.0 + 2.0 * np.array([1.0, -2.0]), rtol=1e-6)


def test_pylayer_create_graph_clear_error():
    class Double(PyLayer):
        @staticmethod
        def forward(ctx, x):
            return x * 2.0

        @staticmethod
        def backward(ctx, g):
            return g * 2.0

    x = paddle.to_tensor(np.array([1.0], np.float32))
    x.stop_gradient = False
    y = ops.sum(Double.apply(x))
    with pytest.raises(RuntimeError, match="not supported through op"):
        paddle.grad(y, [x], create_graph=True)
