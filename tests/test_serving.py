"""Serving engine: page pool, ragged paged-attention decode, continuous
batching, and the end-to-end checkpoint → engine path.

The Pallas kernel runs in interpret mode on the CPU mesh — the same
pallas_call compiles on TPU — so kernel == XLA-reference equality and
scheduler == sequential-GPTGenerator equality are tier-1 assertions."""
import numpy as np
import pytest
import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.serving import (ContinuousBatchingScheduler,
                                EngineShapeError, PagePool, PagePoolError,
                                PagePoolOOM, ServingEngine,
                                simulate_decode_signatures)


def _tiny_model(seed=0):
    from paddle_tpu.models.gpt import (GPTForPretraining, GPTModel,
                                       gpt_tiny_config)
    paddle.seed(seed)
    cfg = gpt_tiny_config()
    return GPTForPretraining(GPTModel(cfg)), cfg


def _prompts(cfg, lens, seed=0):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, cfg.vocab_size, (s,)).astype(np.int32)
            for s in lens]


# ---------------------------------------------------------------- pool

def test_pool_alloc_extend_free_roundtrip():
    pool = PagePool(num_pages=9, page_size=4, num_layers=2,
                    num_kv_heads=2, head_dim=8)
    pages = pool.alloc("a", 5)                 # 2 pages for 5 tokens
    assert len(pages) == 2 and PagePool.SINK not in pages
    assert pool.pages_in_use == 2 and pool.seq_len("a") == 5
    pool.extend("a", 3)                        # 8 tokens: still 2 pages
    assert len(pool.table("a")) == 2
    pool.extend("a", 1)                        # 9th token: page 3
    assert len(pool.table("a")) == 3
    pool.alloc("b", 4)
    assert pool.pages_in_use == 4
    pool.free("a")
    assert pool.pages_in_use == 1 and pool.free_pages == 7
    # freed pages are reused (lowest ids first)
    again = pool.alloc("c", 12)
    assert set(again) & set(pages)


def test_pool_oob_and_oom():
    pool = PagePool(num_pages=4, page_size=4, num_layers=1,
                    num_kv_heads=1, head_dim=4)
    pool.alloc("a", 4)
    with pytest.raises(PagePoolError):
        pool.alloc("a", 2)                     # double alloc
    with pytest.raises(PagePoolError):
        pool.extend("zzz")                     # unknown sequence
    with pytest.raises(PagePoolError):
        pool.free("zzz")
    with pytest.raises(PagePoolError):
        pool.alloc("big", 1000)                # beyond max_seq_len
    with pytest.raises(PagePoolOOM):
        pool.alloc("b", 12)                    # only 2 pages free
    pool.alloc("b", 8)                         # exactly fits
    with pytest.raises(PagePoolOOM):
        pool.extend("b", 1)                    # pool exhausted
    with pytest.raises(ValueError):
        PagePool(num_pages=1, page_size=4, num_layers=1,
                 num_kv_heads=1, head_dim=4)   # sink page needs company


def test_pool_fragmentation_accounting():
    pool = PagePool(num_pages=17, page_size=8, num_layers=1,
                    num_kv_heads=1, head_dim=4)
    pool.alloc("a", 9)    # 2 pages, 7 slots wasted
    pool.alloc("b", 8)    # 1 page, 0 wasted
    st = pool.stats()
    assert st["pages_in_use"] == 3 and st["live_tokens"] == 17
    assert st["utilization"] == round(17 / 24, 4)
    assert st["internal_fragmentation"] == round(1 - 17 / 24, 4)
    pool.free("a")
    pool.free("b")
    assert pool.stats()["internal_fragmentation"] == 0.0


def test_pool_table_and_prefill_rows():
    pool = PagePool(num_pages=9, page_size=4, num_layers=1,
                    num_kv_heads=1, head_dim=4, max_seq_len=16)
    pool.alloc("a", 6)
    tbl = pool.table_array(["a", None])
    assert tbl.shape == (2, 4) and tbl.dtype == np.int32
    assert list(tbl[0, :2]) == pool.table("a")
    assert (tbl[0, 2:] == PagePool.SINK).all()
    assert (tbl[1] == PagePool.SINK).all()      # idle slot: all sink
    assert list(pool.lens_array(["a", None])) == [6, 0]
    rows = pool.prefill_rows("a", 8)
    p0, p1 = pool.table("a")
    assert list(rows[:6]) == [p0 * 4, p0 * 4 + 1, p0 * 4 + 2, p0 * 4 + 3,
                              p1 * 4, p1 * 4 + 1]
    assert (rows[6:] < 4).all()                 # padding rows → sink page


# -------------------------------------------------------------- kernel

def test_paged_decode_kernel_matches_reference_ragged():
    """Pallas ragged paged decode == XLA reference attention on a ragged
    batch (different lengths, idle slot) — acceptance criterion."""
    from paddle_tpu.kernels.paged_attention import (
        paged_attention_decode, paged_attention_reference)
    rng = np.random.default_rng(0)
    B, nh, d, np_, ps, pmax = 4, 4, 16, 13, 8, 4
    q = jnp.asarray(rng.standard_normal((B, nh, d)).astype(np.float32))
    kp = jnp.asarray(rng.standard_normal((np_, ps, nh, d)).astype(
        np.float32))
    vp = jnp.asarray(rng.standard_normal((np_, ps, nh, d)).astype(
        np.float32))
    pt = jnp.asarray(np.array([[1, 2, 3, 4], [5, 0, 0, 0],
                               [6, 7, 0, 0], [0, 0, 0, 0]], np.int32))
    sl = jnp.asarray(np.array([29, 3, 16, 0], np.int32))  # ragged + idle
    out = paged_attention_decode(q, kp, vp, pt, sl)
    ref = paged_attention_reference(q, kp, vp, pt, sl)
    # live slots match exactly; the idle slot only has to stay finite
    np.testing.assert_allclose(np.asarray(out)[:3], np.asarray(ref)[:3],
                               rtol=2e-5, atol=2e-5)
    assert np.isfinite(np.asarray(out)).all()


def test_paged_decode_matches_dense_attention_oracle():
    """Paged gather+mask == plain causal attention over the dense cache:
    scatter a sequence into pages, decode its last token, compare with
    softmax over the raw K/V."""
    from paddle_tpu.kernels.paged_attention import paged_attention_decode
    rng = np.random.default_rng(1)
    nh, d, ps, n = 2, 8, 4, 11
    k_seq = rng.standard_normal((n, nh, d)).astype(np.float32)
    v_seq = rng.standard_normal((n, nh, d)).astype(np.float32)
    q = jnp.asarray(rng.standard_normal((1, nh, d)).astype(np.float32))
    pages = [2, 4, 1]                           # 3 pages hold 11 tokens
    kp = np.zeros((6, ps, nh, d), np.float32)
    vp = np.zeros((6, ps, nh, d), np.float32)
    for t in range(n):
        kp[pages[t // ps], t % ps] = k_seq[t]
        vp[pages[t // ps], t % ps] = v_seq[t]
    out = paged_attention_decode(
        q, jnp.asarray(kp), jnp.asarray(vp),
        jnp.asarray(np.array([pages], np.int32)),
        jnp.asarray(np.array([n], np.int32)))
    s = np.einsum("nd,tnd->nt", np.asarray(q)[0], k_seq) / np.sqrt(d)
    p = np.exp(s - s.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    ref = np.einsum("nt,tnd->nd", p, v_seq)
    np.testing.assert_allclose(np.asarray(out)[0], ref, rtol=2e-5,
                               atol=2e-5)


def test_paged_decode_gqa():
    """num_kv_heads dividing num_heads (MQA/GQA pool layout)."""
    from paddle_tpu.kernels.paged_attention import (
        paged_attention_decode, paged_attention_reference)
    rng = np.random.default_rng(2)
    B, nh, nkv, d, np_, ps = 2, 4, 2, 8, 5, 4
    q = jnp.asarray(rng.standard_normal((B, nh, d)).astype(np.float32))
    kp = jnp.asarray(rng.standard_normal((np_, ps, nkv, d)).astype(
        np.float32))
    vp = jnp.asarray(rng.standard_normal((np_, ps, nkv, d)).astype(
        np.float32))
    pt = jnp.asarray(np.array([[1, 2], [3, 4]], np.int32))
    sl = jnp.asarray(np.array([7, 8], np.int32))
    np.testing.assert_allclose(
        np.asarray(paged_attention_decode(q, kp, vp, pt, sl)),
        np.asarray(paged_attention_reference(q, kp, vp, pt, sl)),
        rtol=2e-5, atol=2e-5)


# ----------------------------------------------------------- scheduler

def test_scheduler_matches_sequential_generator():
    """Continuous batching (ragged prompts, shared pool, bucketed decode)
    reproduces sequential GPTGenerator greedy decode token for token —
    acceptance criterion."""
    from paddle_tpu.models.gpt import GPTGenerator
    model, cfg = _tiny_model()
    gen = GPTGenerator(model, temperature=0.0)
    eng = ServingEngine(model, page_size=8, decode_buckets=(1, 2, 4),
                        aot=True)
    sched = ContinuousBatchingScheduler(eng)
    prompts = _prompts(cfg, (5, 11, 8, 3))
    reqs = [sched.submit(p, max_new_tokens=6) for p in prompts]
    sched.run()
    assert all(r.state == "finished" for r in reqs)
    for p, r in zip(prompts, reqs):
        ref = np.asarray(gen(p[None, :], max_new_tokens=6)._value)[0]
        np.testing.assert_array_equal(r.output_ids, ref,
                                      err_msg=f"prompt len {len(p)}")
    # drained pool: no leaked pages
    assert eng.pool.pages_in_use == 0
    assert sched.steps > 0 and len(sched.step_times) == sched.steps


def test_scheduler_admit_evict_staggered_arrivals():
    """Requests arriving mid-flight join the running batch (admit) and
    finished ones leave (evict) without disturbing other streams."""
    from paddle_tpu.models.gpt import GPTGenerator
    model, cfg = _tiny_model(seed=3)
    gen = GPTGenerator(model, temperature=0.0)
    eng = ServingEngine(model, page_size=8, decode_buckets=(1, 2),
                        aot=False)
    sched = ContinuousBatchingScheduler(eng)
    p1, p2, p3 = _prompts(cfg, (4, 9, 6), seed=3)
    r1 = sched.submit(p1, max_new_tokens=8)
    sched.step(); sched.step()
    r2 = sched.submit(p2, max_new_tokens=3)    # joins mid-flight
    sched.step()
    r3 = sched.submit(p3, max_new_tokens=4)    # queues behind bucket cap
    sched.run()
    for p, r, n in [(p1, r1, 8), (p2, r2, 3), (p3, r3, 4)]:
        ref = np.asarray(gen(p[None, :], max_new_tokens=n)._value)[0]
        np.testing.assert_array_equal(r.output_ids, ref)
    s = r2.summary()
    assert s["state"] == "finished" and s["new_tokens"] == 3
    assert s["queue_wait_s"] >= 0 and s["ttft_s"] > 0


def test_scheduler_page_pressure_queues_requests():
    """Admission reserves the FULL completion: a pool too small for two
    sequences runs them one after the other, both still correct."""
    model, cfg = _tiny_model(seed=4)
    # pool: sink + 4 pages of 8 tokens = room for ONE (prompt 17 + 7)
    eng = ServingEngine(model, page_size=8, num_pages=5,
                        max_seq_len=32, decode_buckets=(1, 2), aot=False)
    sched = ContinuousBatchingScheduler(eng)
    pa, pb = _prompts(cfg, (17, 18), seed=4)
    ra = sched.submit(pa, max_new_tokens=7)
    rb = sched.submit(pb, max_new_tokens=7)
    sched.step()
    assert ra.state == "running" and rb.state == "queued"
    sched.run()
    assert ra.state == rb.state == "finished"
    assert len(ra.tokens) == len(rb.tokens) == 7
    assert eng.pool.pages_in_use == 0


def test_scheduler_rejects_oversized_and_eos():
    model, cfg = _tiny_model(seed=5)
    eng = ServingEngine(model, page_size=8, max_seq_len=32,
                        decode_buckets=(1, 2), aot=False)
    sched = ContinuousBatchingScheduler(eng)
    big = sched.submit(np.zeros(30, np.int32), max_new_tokens=10)
    assert big.state == "rejected"
    # max_new < 1 is unservable (prefill always emits one token) and
    # must bounce at submit, not crash the loop at admission
    zero = sched.submit(np.zeros(32, np.int32), max_new_tokens=0)
    assert zero.state == "rejected"
    # eos: find the greedy first token, then ask for it as the stop id
    (p,) = _prompts(cfg, (6,), seed=5)
    probe = sched.submit(p, max_new_tokens=1)
    sched.run()
    eos = probe.tokens[0]
    r = sched.submit(p, max_new_tokens=10, eos_id=eos)
    sched.run()
    assert r.state == "finished" and r.tokens == [eos]


def test_engine_shape_errors_and_aot_closure():
    """The AOT bucket set is closed at init: unknown decode batches and
    oversized prompts raise instead of recompiling; the randomized
    admission-mix simulation stays inside the set."""
    model, _ = _tiny_model(seed=6)
    eng = ServingEngine(model, page_size=8, decode_buckets=(1, 2),
                        aot=True)
    assert set(eng._decode_exe) == {1, 2}
    assert set(eng._prefill_exe) == set(eng.prefill_buckets)
    with pytest.raises(EngineShapeError):
        eng.decode_bucket(3)
    with pytest.raises(EngineShapeError):
        eng.prefill_bucket(10_000)
    with pytest.raises(EngineShapeError):
        eng.prefill("x", np.zeros(128, np.int32))  # no room to decode
    used_d, used_p, ok_d, ok_p = simulate_decode_signatures(
        eng.decode_buckets, eng.prefill_buckets, eng.pool.page_size,
        eng.pool.num_pages, eng.max_seq_len, n_requests=120, seed=7)
    assert used_d and used_d <= ok_d
    assert used_p and used_p <= ok_p


def test_engine_no_recompile_across_mix():
    """Serving a shuffled request mix never grows the compiled-program
    set beyond the AOT buckets (zero retraces at serving time)."""
    model, cfg = _tiny_model(seed=7)
    eng = ServingEngine(model, page_size=8, decode_buckets=(1, 2, 4),
                        aot=True)
    n_exe = len(eng._decode_exe) + len(eng._prefill_exe)
    compile_s0 = eng.compile_s
    sched = ContinuousBatchingScheduler(eng)
    for i, p in enumerate(_prompts(cfg, (3, 21, 9, 14, 5, 40), seed=8)):
        sched.submit(p, max_new_tokens=2 + i % 4)
    sched.run()
    assert len(eng._decode_exe) + len(eng._prefill_exe) == n_exe
    assert eng.compile_s == compile_s0


# ------------------------------------------------- engine from checkpoint

def test_engine_end_to_end_from_checkpoint(tmp_path):
    """checkpoint-load → generator → scheduler: a paddle.save'd state
    dict serves identically to the live model."""
    from paddle_tpu.models.gpt import gpt_tiny_config
    model, cfg = _tiny_model(seed=9)
    path = str(tmp_path / "gpt.pdparams")
    paddle.save(model.state_dict(), path)

    eng = ServingEngine.from_checkpoint(path, gpt_tiny_config(),
                                        page_size=8,
                                        decode_buckets=(1, 2), aot=False)
    live = ServingEngine(model, page_size=8, decode_buckets=(1, 2),
                         aot=False)
    prompts = _prompts(cfg, (7, 12), seed=9)
    outs = []
    for e in (eng, live):
        sched = ContinuousBatchingScheduler(e)
        reqs = [sched.submit(p, max_new_tokens=5) for p in prompts]
        sched.run()
        outs.append([r.output_ids for r in reqs])
    for a, b in zip(*outs):
        np.testing.assert_array_equal(a, b)


# ------------------------------------------------------------ telemetry

def test_serving_telemetry_and_flight_recorder():
    """Serving steps land in the paddle_serving_* metric family AND the
    flight recorder / anomaly path, like train steps."""
    from paddle_tpu.observability import get_registry
    from paddle_tpu.observability.flight import get_flight_recorder
    model, cfg = _tiny_model(seed=10)
    reg = get_registry()

    def val(name, **labels):
        inst = reg.get(name)
        if inst is None:
            return 0.0
        total = 0.0
        for lab, state in inst.collect():
            if all(dict(lab).get(k) == v for k, v in labels.items()):
                total += state.get("value", state.get("count", 0.0))
        return total

    sub0 = val("paddle_serving_requests_total", event="submitted")
    fin0 = val("paddle_serving_requests_total", event="finished")
    tok0 = val("paddle_serving_tokens_out_total")
    eng = ServingEngine(model, page_size=8, decode_buckets=(1, 2),
                        aot=False)
    sched = ContinuousBatchingScheduler(eng)
    reqs = [sched.submit(p, max_new_tokens=4)
            for p in _prompts(cfg, (5, 9), seed=10)]
    sched.run()
    assert val("paddle_serving_requests_total", event="submitted") \
        == sub0 + 2
    assert val("paddle_serving_requests_total", event="finished") \
        == fin0 + 2
    assert val("paddle_serving_tokens_out_total") \
        == tok0 + sum(len(r.tokens) for r in reqs)
    ttft = reg.get("paddle_serving_ttft_seconds")
    assert ttft is not None and ttft.count >= 2
    assert reg.get("paddle_serving_kv_pages_in_use") is not None
    # flight recorder saw serving-path steps
    recs = get_flight_recorder().records()
    serving_steps = [r for r in recs
                     if r.get("kind") == "step"
                     and r.get("path") == "serving"]
    assert len(serving_steps) >= sched.steps


# ----------------------------------------------------------- lint gate

def test_check_program_serving_gate_clean():
    """tools/check_program.py --model serving: the decode-step pass
    suite AND the bucket-closure proof both report clean."""
    import importlib.util
    import os
    spec = importlib.util.spec_from_file_location(
        "check_program", os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "tools", "check_program.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    reports = mod.lint_model("serving", hbm_budget_gb=16)
    assert len(reports) == 4
    for rep in reports:
        assert rep.clean, str(rep)
    assert {r.target_name for r in reports} == {
        "serving.decode_step", "serving.decode_buckets",
        "serving.chunk_prefill", "serving.moe_decode_step"}


# ------------------------------------------------------------- predict

def test_predicted_serving_row_tiny():
    """The serving_predicted row: cost model over the real decode jaxpr,
    abstract shapes only — numbers present and positive."""
    from paddle_tpu.serving.predict import predicted_serving_row
    row = predicted_serving_row("tiny", concurrency=4, page_size=8)
    assert row["predicted_tokens_per_sec"] > 0
    assert row["predicted_decode_step_ms"] > 0
    assert row["predicted_per_token_ms_p95"] >= \
        row["predicted_per_token_ms_p50"]
    assert row["concurrency"] == 4 and row["chip_assumed"] == "v5e"
