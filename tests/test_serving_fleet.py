"""Fleet serving: prefix-affinity router over N engine replicas.

Coverage:

- routing policy (pure): affinity key / rendezvous stability under
  membership change, least-loaded fallback, round-robin baseline;
- autoscaler policy (pure, fake clock): scale-out fires only on a
  SUSTAINED recorded burn series, cooldown gates, idle scale-in;
- scheduler drain state + /healthz "draining" (satellite);
- federated folding + doctor fleet section: router_queue bucket sums
  exactly, straggler replica named, fixture-dir CLI gate rc=0;
- the fleet-predicted anchor (per-replica roofline x N);
- REAL fleets (replica processes via distributed.spawn): end-to-end
  shared-prefix serving with from_checkpoint warm start + federation +
  fleet /status + federated /metrics + drain-then-retire scale-in, and
  the ACCEPTANCE replica-SIGKILL-under-load test (goodput recovers,
  zero failed requests, requeued rids in the fleet requests stream).
"""
import glob
import json
import os
import shutil
import time
import urllib.request

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models.gpt import gpt_tiny_config
from paddle_tpu.serving.router import (PrefixAffinityRouter, SLOAutoscaler,
                                       affinity_key, rendezvous_order)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURE = os.path.join(REPO, "tests", "fixtures", "fleet_doctor_run")


def _fleet_cfg():
    return gpt_tiny_config(num_layers=2, hidden_size=32, num_heads=2,
                           max_position_embeddings=64)


ENGINE_KW = dict(page_size=8, decode_buckets=(1, 2, 4, 8),
                 prefill_chunk=8, prefix_cache=True)


# ===========================================================================
# routing policy (pure)
# ===========================================================================

def _snap(**kw):
    d = {"healthy": True, "draining": False, "queue_depth": 0,
         "pending": 0, "free_pages": 50, "num_pages": 64}
    d.update(kw)
    return d


def test_affinity_key_is_page_block_granular():
    a = affinity_key([1, 2, 3, 4, 5, 6], 4)
    b = affinity_key([1, 2, 3, 4, 9, 9, 9], 4)   # same first block
    c = affinity_key([1, 2, 3, 5, 5, 6], 4)      # diverges inside block
    assert a == b and a != c


def test_rendezvous_stable_under_membership_change():
    """Removing a replica must only remap keys IT owned — every other
    key keeps its winner (the property that preserves cache affinity
    through elastic scale-in/out)."""
    keys = [affinity_key([i, i + 1, i + 2], 3) for i in range(64)]
    owner4 = {k: rendezvous_order(k, [0, 1, 2, 3])[0] for k in keys}
    owner3 = {k: rendezvous_order(k, [0, 1, 2])[0] for k in keys}
    moved = [k for k in keys if owner4[k] != owner3[k]]
    # only keys owned by the removed replica 3 may move
    assert all(owner4[k] == 3 for k in moved)
    assert any(owner4[k] == 3 for k in keys)
    # and they move to their rendezvous runner-up
    for k in moved:
        assert owner3[k] == rendezvous_order(k, [0, 1, 2, 3])[1]


def test_affinity_routes_same_prefix_together_and_falls_back():
    r = PrefixAffinityRouter(block_tokens=4, max_queue_depth=4)
    snaps = {0: _snap(), 1: _snap()}
    prompt = np.arange(10)
    first = r.route(prompt, snaps)
    assert all(r.route(prompt, snaps) == first for _ in range(5))
    assert r.last_outcome == "affinity"
    # saturate the preferred replica: fall back to the least-loaded one
    snaps[first]["queue_depth"] = 4
    other = 1 - first
    snaps[other]["pending"] = 1
    assert r.route(prompt, snaps) == other
    assert r.last_outcome == "fallback" and r.fallbacks == 1
    # draining replicas are never routed to; none eligible -> None
    snaps[first]["queue_depth"] = 0
    snaps[first]["draining"] = True
    snaps[other]["draining"] = True
    assert r.route(prompt, snaps) is None
    st = r.stats()
    assert st["routed"] == 7 and st["affinity_hits"] == 6


def test_least_loaded_and_round_robin_policies():
    rr = PrefixAffinityRouter(policy="round_robin")
    snaps = {0: _snap(), 1: _snap(), 2: _snap()}
    assert [rr.route([1], snaps) for _ in range(6)] == [0, 1, 2, 0, 1, 2]
    ll = PrefixAffinityRouter(policy="least_loaded")
    snaps[0]["pending"] = 5
    snaps[1]["pending"] = 1
    snaps[2]["pending"] = 1
    snaps[2]["free_pages"] = 60          # emptier pool breaks the tie
    assert ll.route([1], snaps) == 2
    with pytest.raises(ValueError):
        PrefixAffinityRouter(policy="bogus")


# ===========================================================================
# autoscaler policy (pure, fake clock / recorded burn series)
# ===========================================================================

def test_autoscaler_scale_out_on_sustained_burn_only():
    """ACCEPTANCE (policy half): a recorded burn series on a fake clock
    — one hot sample does NOT scale; a burn sustained past sustain_s
    does, exactly once per cooldown window."""
    a = SLOAutoscaler(min_replicas=1, max_replicas=4, scale_out_burn=1.0,
                      sustain_s=2.0, idle_s=10.0, cooldown_s=5.0,
                      clock=lambda: 0.0)
    # blip: hot for one sample, then cool — never fires
    assert a.observe(2, 3.0, True, now=0.0)["action"] is None
    assert a.observe(2, 0.1, True, now=1.0)["action"] is None
    # recorded sustained-burn series: hot from t=2 .. t=5
    actions = []
    for t, burn in [(2.0, 1.5), (3.0, 1.8), (4.0, 2.2), (4.5, 2.0),
                    (5.0, 1.9)]:
        actions.append(a.observe(2, burn, True, now=t)["action"])
    assert actions[:2] == [None, None]          # window not covered yet
    assert "scale_out" in actions
    fired_at = actions.index("scale_out")
    # cooldown: everything after the firing within 5s stays None
    assert all(x is None for x in actions[fired_at + 1:])
    # still burning after cooldown: fires again, capped at max_replicas
    d = a.observe(3, 2.0, True, now=11.0)
    assert d["action"] == "scale_out"
    assert a.observe(4, 2.0, True, now=17.0)["action"] is None  # at max
    assert len(a.decisions) == 2


def test_autoscaler_router_queue_counts_as_burn():
    """A saturated router queue is future burn — scale-out must fire
    even before the replica SLO windows have enough samples."""
    a = SLOAutoscaler(max_replicas=2, sustain_s=1.0, cooldown_s=99.0)
    a.observe(1, 0.0, True, router_queue_depth=5, now=0.0)
    d = a.observe(1, 0.0, True, router_queue_depth=5, now=1.1)
    assert d["action"] == "scale_out"


def test_autoscaler_scale_in_after_idle_window():
    a = SLOAutoscaler(min_replicas=1, max_replicas=4, idle_s=4.0,
                      idle_burn=0.25, cooldown_s=1.0)
    assert a.observe(2, 0.0, False, now=0.0)["action"] is None
    assert a.observe(2, 0.0, False, now=2.0)["action"] is None
    d = a.observe(2, 0.1, False, now=4.5)
    assert d["action"] == "scale_in" and "idle" in d["reason"]
    # at min_replicas: never scales below the floor
    a2 = SLOAutoscaler(min_replicas=1, idle_s=1.0, cooldown_s=0.0)
    a2.observe(1, 0.0, False, now=0.0)
    assert a2.observe(1, 0.0, False, now=2.0)["action"] is None
    # busy samples inside the window block scale-in
    a3 = SLOAutoscaler(min_replicas=1, idle_s=4.0, cooldown_s=0.0)
    a3.observe(2, 0.0, False, now=0.0)
    a3.observe(2, 0.0, True, now=2.0)
    assert a3.observe(2, 0.0, False, now=4.5)["action"] is None
    with pytest.raises(ValueError):
        SLOAutoscaler(min_replicas=3, max_replicas=2)


# ===========================================================================
# scheduler drain + /healthz draining (satellite)
# ===========================================================================

def test_scheduler_drain_rejects_new_and_healthz_reports_draining():
    from paddle_tpu.serving.scheduler import (ContinuousBatchingScheduler,
                                              _ShapeProbeEngine)
    eng = _ShapeProbeEngine(decode_buckets=(1, 2), prefill_buckets=(8, 32),
                            page_size=8, num_pages=32, max_seq_len=32)
    sched = ContinuousBatchingScheduler(eng)
    r0 = sched.submit(np.zeros(6, np.int32), 3)
    sched.drain()
    r1 = sched.submit(np.zeros(6, np.int32), 3)
    assert r1.state == "rejected" and r1.reject_reason == "draining"
    assert sched.status()["draining"] is True
    srv = sched.serve_http(port=0)
    try:
        with urllib.request.urlopen(srv.url + "/healthz", timeout=5) as rsp:
            assert rsp.status == 200
            assert rsp.read().decode().strip() == "draining"
    finally:
        srv.close()
    # draining still FINISHES in-flight work (drain-then-retire contract)
    sched.run()
    assert r0.state == "finished" and len(r0.tokens) == 3


def test_scheduler_submit_threads_global_rid_and_router_wait():
    from paddle_tpu.serving.scheduler import (ContinuousBatchingScheduler,
                                              _ShapeProbeEngine)
    eng = _ShapeProbeEngine(decode_buckets=(1, 2), prefill_buckets=(8, 32),
                            page_size=8, num_pages=32, max_seq_len=32)
    sched = ContinuousBatchingScheduler(eng)
    r = sched.submit(np.zeros(4, np.int32), 2, rid=1234,
                     router_wait_s=0.25)
    assert r.rid == 1234
    sched.run()
    s = r.summary()
    assert s["rid"] == 1234 and s["router_wait_s"] == 0.25


# ===========================================================================
# federated folding + doctor fleet section
# ===========================================================================

def _fleet_records():
    recs = []
    for rank, mean in ((0, 0.010), (1, 0.030)):
        for i in range(3):
            recs.append({
                "event": "request", "rank": rank, "rid": rank * 3 + i,
                "state": "finished", "new_tokens": 8,
                "router_wait_s": 0.05, "queue_wait_s": 0.01,
                "prefill_s": 0.02, "decode_s": mean * 7,
                "ttft_s": 0.031, "total_s": 0.031 + mean * 7,
                "per_token_s": {"count": 8, "mean": mean, "p50": mean,
                                "p95": mean, "p99": mean, "max": mean},
            })
    return recs


def test_fold_per_replica_and_router_wait_totals():
    from paddle_tpu.observability.reqtrace import fold_request_records
    sv = fold_request_records(_fleet_records())
    assert sv["router_wait_seconds_total"] == pytest.approx(0.3)
    per = sv["per_replica"]
    assert set(per) == {"0", "1"}
    assert per["0"]["requests"] == 3 and per["0"]["new_tokens"] == 24
    assert per["1"]["per_token_s_mean"] == pytest.approx(0.030)
    # single-replica records: no per_replica section
    single = fold_request_records(
        [r for r in _fleet_records() if r["rank"] == 0])
    assert "per_replica" not in single


def test_serving_attribution_router_queue_bucket_sums_exactly():
    from paddle_tpu.observability.doctor import attribute_serving_gap
    from paddle_tpu.observability.reqtrace import fold_request_records
    summary = {"serving": fold_request_records(_fleet_records()),
               "compile": {"seconds": 0.48}}
    pred = {"predicted_decode_step_ms": 5.0,
            "predicted_per_token_ms_p50": 5.0}
    attr = attribute_serving_gap(summary, pred)
    assert "router_queue" in attr["buckets"]
    assert attr["buckets"]["router_queue"] == pytest.approx(
        0.3 / 48 * 1e3, abs=1e-6)
    assert sum(attr["buckets"].values()) == pytest.approx(
        attr["delta_ms"], abs=1e-6)
    # fleet section names the straggler replica (0.030 vs median 0.020)
    fleet = attr["fleet"]
    assert fleet["replicas"] == 2
    assert fleet["straggler"]["replica"] == "1"
    assert fleet["straggler"]["skew"] == pytest.approx(1.5)
    # a router-less single-replica run keeps the classic 4-bucket shape
    solo = [dict(r, router_wait_s=0.0) for r in _fleet_records()
            if r["rank"] == 0]
    attr1 = attribute_serving_gap(
        {"serving": fold_request_records(solo)}, pred)
    assert set(attr1["buckets"]) == {"queue", "prefill", "compile",
                                     "decode"}
    assert "fleet" not in attr1


def test_perf_doctor_cli_fleet_fixture_gate(tmp_path, capsys):
    """Tier-1 gate: the checked-in federated fleet fixture diagnoses
    rc=0 with the router_queue bucket, the named straggler replica, and
    the relaunch accounted — without writing into the fixture."""
    from tools.perf_doctor import main as doctor_main
    assert doctor_main([FIXTURE, "--no-write"]) == 0
    out = capsys.readouterr().out
    assert "router_queue" in out
    assert "straggler_replica" in out or "fleet straggler" in out
    assert not os.path.exists(os.path.join(FIXTURE, "run_summary.json"))
    assert doctor_main([FIXTURE, "--no-write", "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    sattr = doc["serving_attribution"]
    assert sum(sattr["buckets"].values()) == pytest.approx(
        sattr["delta_ms"], abs=0.01)
    assert sattr["fleet"]["straggler"]["replica"] == "2"
    assert doc["summary"]["restarts"] == 1
    kinds = {f["kind"] for f in doc["findings"]}
    assert "straggler_replica" in kinds


# ===========================================================================
# fleet-predicted anchor
# ===========================================================================

def test_predicted_fleet_row_shape_and_orderings():
    from paddle_tpu.serving.predict import predicted_fleet_row
    row = predicted_fleet_row("tiny", replicas=2, n_requests=16,
                              concurrency=8, prompt_len=48,
                              shared_fraction=0.75, max_new=8,
                              prefill_chunk=16, page_size=16)
    assert row["predicted_tokens_per_sec"] > 0
    # affinity >= round robin (more cache hits, same roofline)
    assert row["predicted_tokens_per_sec"] \
        >= row["predicted_tokens_per_sec_round_robin"]
    assert row["predicted_affinity_speedup_vs_round_robin"] >= 1.0
    assert row["predicted_prefix_hit_rate"] \
        > row["predicted_prefix_hit_rate_round_robin"]
    assert row["predicted_ttft_ms_mean"] \
        <= row["predicted_ttft_ms_mean_round_robin"]
    assert row["predicted_ttft_ms_hit"] < row["predicted_ttft_ms_miss"]
    # N replicas beat one replica on the same workload
    assert row["predicted_tokens_per_sec"] \
        > row["predicted_tokens_per_sec_single_replica"]
    assert 0 < row["predicted_scaling_efficiency"] <= 1.2


# ===========================================================================
# real fleets (replica processes)
# ===========================================================================

def _drain_env(monkeypatch, tmp_path):
    # fleet replicas inherit the parent env; make sure a pytest-level
    # telemetry dir never leaks into the fleet run dir
    monkeypatch.delenv("PADDLE_TELEMETRY_DIR", raising=False)
    monkeypatch.delenv("PADDLE_REQUESTS_PER_RANK", raising=False)


def test_fleet_end_to_end_warm_start_federation_and_drain_retire(
        tmp_path, monkeypatch):
    """One real 2-replica fleet, end to end: from_checkpoint warm start,
    shared-prefix workload routed with affinity (aggregate prefix hit
    rate > 0 in the FEDERATED pool stats), fleet /status + federated
    /metrics over HTTP, then scale-in mid-load — drain-then-retire
    finishes every in-flight request before the replica goes away."""
    from paddle_tpu.models.gpt import GPTForPretraining, GPTModel
    from paddle_tpu.serving.fleet import FleetRouter
    from paddle_tpu.serving.prefix_cache import make_shared_prefix_workload
    _drain_env(monkeypatch, tmp_path)
    cfg = _fleet_cfg()
    paddle.seed(7)
    ckpt = str(tmp_path / "gpt.pdparams")
    paddle.save(GPTForPretraining(GPTModel(cfg)).state_dict(), ckpt)

    fleet = FleetRouter(cfg, checkpoint=ckpt, n_replicas=2,
                        engine_kwargs=dict(ENGINE_KW),
                        run_dir=str(tmp_path / "run"),
                        slo={"ttft_p95_s": 60.0}, seed=7)
    try:
        fleet.start()
        prompts = make_shared_prefix_workload(cfg.vocab_size, 9, 16, 4,
                                              n_prefixes=3, seed=2)
        rids = [fleet.submit(p, max_new_tokens=4) for p in prompts]
        assert fleet.run(timeout=180)
        assert all(fleet.results[r]["state"] == "finished" for r in rids)
        assert all(len(fleet.results[r]["tokens"]) == 4 for r in rids)

        status = fleet.fleet_status()
        assert status["healthy"] and status["n_replicas"] == 2
        # affinity fed the prefix caches: federated hit accounting
        agg = status["pool_aggregate"]
        assert agg["prefix_hits"] > 0 and agg["prefix_hit_rate"] > 0.5
        assert status["routing"]["policy"] == "affinity"
        assert status["routing"]["routed"] >= 9

        # fleet endpoint: /status JSON + federated /metrics with
        # replica-relabeled series
        srv = fleet.serve_http()
        try:
            with urllib.request.urlopen(srv.url + "/status",
                                        timeout=10) as rsp:
                doc = json.loads(rsp.read().decode())
            assert set(doc["replicas"]) == {"0", "1"}
            assert doc["pool_aggregate"]["prefix_hits"] > 0
            with urllib.request.urlopen(srv.url + "/metrics",
                                        timeout=10) as rsp:
                expo = rsp.read().decode()
            assert 'replica="0"' in expo and 'replica="1"' in expo
            assert "paddle_serving_requests_total" in expo
            with urllib.request.urlopen(srv.url + "/healthz",
                                        timeout=10) as rsp:
                assert rsp.read().decode().strip() == "ok"
        finally:
            srv.close()

        # scale-in WITH work in flight: drain-then-retire must complete
        # everything before the replica retires
        more = [fleet.submit(p, max_new_tokens=4) for p in prompts]
        retired = fleet.scale_in(reason="test")
        assert retired is not None
        assert fleet.run(timeout=180)
        assert all(fleet.results[r]["state"] == "finished" for r in more)
        deadline = time.monotonic() + 60
        while retired in fleet.replicas and time.monotonic() < deadline:
            fleet.tick()
            time.sleep(0.05)
        assert retired not in fleet.replicas
        assert len(fleet.replicas) == 1

        summary = fleet.shutdown()
    finally:
        fleet.shutdown(federate=False)
    # federation: one run_summary over every replica's streams
    assert os.path.exists(os.path.join(fleet.run_dir, "run_summary.json"))
    sv = summary["serving"]
    assert sv["finished"] == 18
    assert sv["cached_prefix_tokens_total"] > 0
    assert summary["fleet"]["replicas_launched"] == 2
    assert summary["fleet"]["router"]["policy"] == "affinity"
    assert summary["fleet"]["router_results"] == {"finished": 18}
    assert summary["fleet"]["restarts"] == 0
    ev = summary["events"]
    assert ev.get("replica_start") == 2
    assert ev.get("fleet_scale") == 1 and ev.get("replica_retired") == 1


def test_fleet_moe_checkpoint_warm_start_kill_under_load(
        tmp_path, monkeypatch):
    """MoE-checkpoint warm start (owed from PR 14): FleetRouter replicas
    build their engine via ``MoEServingEngine.from_checkpoint``, and the
    kill-under-load harness still holds — a SIGKILLed warm-started MoE
    replica is replaced (itself warm-started from the same checkpoint)
    with zero failed requests."""
    from paddle_tpu.distributed.fleet.elastic.fault_injection import \
        kill_replica
    from paddle_tpu.models import (ErnieMoeForPretraining, ErnieMoeModel,
                                   ernie_moe_tiny_config)
    from paddle_tpu.serving.fleet import FleetRouter
    _drain_env(monkeypatch, tmp_path)
    cfg = ernie_moe_tiny_config(
        num_hidden_layers=2, hidden_size=32, num_attention_heads=2,
        intermediate_size=64, num_experts=4, capacity_factor=100.0,
        max_position_embeddings=64)
    paddle.seed(11)
    ckpt = str(tmp_path / "ernie_moe.pdparams")
    paddle.save(ErnieMoeForPretraining(ErnieMoeModel(cfg)).state_dict(),
                ckpt)

    fleet = FleetRouter(cfg, checkpoint=ckpt, n_replicas=2,
                        model_kind="moe",
                        engine_kwargs=dict(page_size=8,
                                           decode_buckets=(1, 2, 4)),
                        run_dir=str(tmp_path / "run"), seed=11,
                        max_restarts=3)
    rng = np.random.default_rng(3)
    try:
        fleet.start()
        rids, killed = [], False
        n_total = 8
        deadline = time.monotonic() + 240
        while len(fleet.results) < n_total:
            assert time.monotonic() < deadline, (
                f"stalled: {len(fleet.results)}/{n_total} done, "
                f"outstanding={fleet.outstanding}")
            if len(rids) < n_total:
                p = rng.integers(0, cfg.vocab_size, (10,)).astype(np.int32)
                rids.append(fleet.submit(p, max_new_tokens=4))
            fleet.tick()
            if not killed and len(fleet.results) >= 1 and fleet._inflight:
                target = next(
                    (rec["replica"] for rec in fleet._inflight.values()
                     if rec.get("replica") is not None), None)
                if target is not None:
                    kill_replica(fleet, target)
                    killed = True
            time.sleep(0.01)
        assert killed
        states = {fleet.results[r]["state"] for r in rids}
        assert states == {"finished"}          # zero failed requests
        assert all(len(fleet.results[r]["tokens"]) == 4 for r in rids)
        assert fleet.restarts >= 1
        summary = fleet.shutdown()
    finally:
        fleet.shutdown(federate=False)
    assert summary["fleet"]["restarts"] >= 1
    # every replica start (initial pair + relaunch) was warm-started
    events = []
    for path in glob.glob(os.path.join(fleet.run_dir, "events.rank*.jsonl")):
        with open(path) as f:
            events += [json.loads(ln) for ln in f if ln.strip()]
    starts = [e for e in events if e.get("event") == "replica_start"]
    assert len(starts) >= 3                    # 2 initial + >=1 relaunch
    assert all(e.get("warm_start") for e in starts)
    assert all(e.get("engine") == "MoEServingEngine" for e in starts)


def test_fleet_replica_sigkill_under_load_zero_failed_requests(
        tmp_path, monkeypatch):
    """ACCEPTANCE: SIGKILL a replica under sustained load. Goodput
    recovers (every submitted request finishes), ZERO failed requests,
    the re-enqueued rids are visible in the fleet requests stream, and
    the federated summary counts the relaunch."""
    from paddle_tpu.distributed.fleet.elastic.fault_injection import \
        kill_replica
    from paddle_tpu.serving.fleet import FleetRouter
    _drain_env(monkeypatch, tmp_path)
    cfg = _fleet_cfg()
    fleet = FleetRouter(cfg, n_replicas=2,
                        engine_kwargs=dict(ENGINE_KW),
                        run_dir=str(tmp_path / "run"), seed=0,
                        max_restarts=3)
    rng = np.random.default_rng(0)
    try:
        fleet.start()
        rids, killed = [], False
        n_total = 14
        deadline = time.monotonic() + 240
        while len(fleet.results) < n_total:
            assert time.monotonic() < deadline, (
                f"stalled: {len(fleet.results)}/{n_total} done, "
                f"outstanding={fleet.outstanding}")
            if len(rids) < n_total:
                p = rng.integers(0, cfg.vocab_size, (12,)).astype(np.int32)
                rids.append(fleet.submit(p, max_new_tokens=6))
            fleet.tick()
            if not killed and len(fleet.results) >= 2 and fleet._inflight:
                target = next(
                    (rec["replica"] for rec in fleet._inflight.values()
                     if rec.get("replica") is not None), None)
                if target is not None:
                    kill_replica(fleet, target)
                    killed = True
            time.sleep(0.01)
        assert killed
        states = {fleet.results[r]["state"] for r in rids}
        assert states == {"finished"}          # zero failed requests
        assert all(len(fleet.results[r]["tokens"]) == 6 for r in rids)
        assert fleet.restarts >= 1
        assert fleet.requeued_rids              # work WAS in flight
        summary = fleet.shutdown()
    finally:
        fleet.shutdown(federate=False)
    assert summary["restarts"] >= 1
    assert summary["fleet"]["requeued_rids"] == sorted(
        set(fleet.requeued_rids))
    assert summary["fleet"]["router_results"] == {"finished": 14}
    # the federated run dir carries every request's terminal record and
    # the requeue black-box lines naming the survived rids
    lines = []
    for path in glob.glob(os.path.join(fleet.run_dir, "requests*.jsonl")):
        with open(path) as f:
            lines += [json.loads(ln) for ln in f if ln.strip()]
    requeue = [r for r in lines if r.get("event") == "request_requeue"]
    assert {r["rid"] for r in requeue} == set(fleet.requeued_rids)
    finished = {r["rid"] for r in lines
                if r.get("event") == "request" and
                r.get("state") == "finished"}
    assert finished == set(rids)
