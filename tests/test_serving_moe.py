"""ERNIE-MoE as a serving workload (serving/moe_engine.py).

Acceptance contract: greedy decode parity between the paged MoE serving
engine (fused Pallas dispatch inside the decode/prefill programs) and
eager ERNIE-MoE generation, the AOT bucket closure, the check_program
gate surface, and the ``serving_moe_predicted`` / fused-dispatch
anchors.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models import (ErnieMoeForPretraining, ErnieMoeModel,
                               ernie_moe_tiny_config)
from paddle_tpu.models.ernie import (ErnieMoeGenerator,
                                     stack_ernie_moe_weights)
from paddle_tpu.serving import (ContinuousBatchingScheduler,
                                EngineShapeError, MoEServingEngine,
                                simulate_decode_signatures)


def _tiny_cfg(**kw):
    base = dict(num_hidden_layers=2, hidden_size=32,
                num_attention_heads=2, intermediate_size=64,
                num_experts=4, capacity_factor=100.0,
                max_position_embeddings=64)
    base.update(kw)
    return ernie_moe_tiny_config(**base)


@pytest.fixture(scope="module")
def moe_model():
    paddle.seed(0)
    cfg = _tiny_cfg()
    model = ErnieMoeForPretraining(ErnieMoeModel(cfg))
    model.eval()
    return cfg, model


@pytest.fixture(scope="module")
def moe_engine(moe_model):
    _, model = moe_model
    return MoEServingEngine(model, page_size=8, decode_buckets=(1, 2, 4))


def test_stacked_weights_shapes_and_kinds(moe_model):
    cfg, model = moe_model
    params, kinds = stack_ernie_moe_weights(model)
    assert kinds == ("dense", "moe")
    assert params["wte"].shape == (cfg.vocab_size, cfg.hidden_size)
    moe_p = params["layers"][1]
    assert moe_p["ew1"].shape == (cfg.num_experts, cfg.hidden_size,
                                  cfg.intermediate_size)
    assert moe_p["gate_w"].shape == (cfg.hidden_size, cfg.num_experts)
    dense_p = params["layers"][0]
    assert dense_p["w1"].shape == (cfg.hidden_size, cfg.intermediate_size)
    assert "gate_w" not in dense_p
    assert params["head"]["dw"].shape == (cfg.vocab_size, cfg.hidden_size)
    with pytest.raises(TypeError):
        stack_ernie_moe_weights(model.ernie)


def test_engine_greedy_parity_vs_eager_generator(moe_model, moe_engine):
    """The acceptance oracle: paged incremental decode through the MoE
    engine == eager full-recompute causal generation, token for token."""
    cfg, model = moe_model
    eng = moe_engine
    gen = ErnieMoeGenerator(model)
    rng = np.random.default_rng(0)
    for i, n in enumerate((7, 3, 12)):
        prompt = rng.integers(0, cfg.vocab_size, (n,)).astype(np.int32)
        want = gen(prompt, max_new_tokens=5)[0]
        sid = 100 + i
        toks = [eng.prefill(sid, prompt)]
        for _ in range(4):
            eng.pool.extend(sid, 1)
            toks.append(eng.decode([sid])[0])
        eng.release(sid)
        np.testing.assert_array_equal(np.asarray(toks), np.asarray(want),
                                      err_msg=f"prompt len {n}")


def test_scheduler_batched_parity(moe_model, moe_engine):
    """Continuous batching over ragged concurrent streams produces the
    same tokens as sequential eager generation for every request."""
    cfg, model = moe_model
    sched = ContinuousBatchingScheduler(moe_engine)
    gen = ErnieMoeGenerator(model)
    rng = np.random.default_rng(1)
    prompts = [rng.integers(0, cfg.vocab_size, (int(n),)).astype(np.int32)
               for n in (5, 9, 3, 12)]
    reqs = [sched.submit(p, max_new_tokens=5) for p in prompts]
    sched.run()
    assert all(r.state == "finished" for r in reqs)
    for p, r in zip(prompts, reqs):
        want = gen(p, max_new_tokens=5)[0]
        np.testing.assert_array_equal(np.asarray(r.tokens),
                                      np.asarray(want))


def test_unfused_reference_engine_matches_fused(moe_model):
    """use_fused_moe=False (the gather-based modelable path) decodes the
    same greedy tokens — kernel and reference are interchangeable in
    the program."""
    cfg, model = moe_model
    rng = np.random.default_rng(2)
    prompt = rng.integers(0, cfg.vocab_size, (6,)).astype(np.int32)

    def run(fused):
        eng = MoEServingEngine(model, page_size=8, decode_buckets=(1, 2),
                               use_fused_moe=fused, aot=False)
        toks = [eng.prefill(0, prompt)]
        for _ in range(3):
            eng.pool.extend(0, 1)
            toks.append(eng.decode([0])[0])
        return toks

    assert run(True) == run(False)


def test_aot_bucket_closure(moe_model, moe_engine):
    cfg, model = moe_model
    eng = moe_engine
    assert eng.decode_signatures() == {
        (b, eng.pool.max_pages_per_seq) for b in (1, 2, 4)}
    assert eng.prefill_signatures() == {
        (1, sb) for sb in eng.prefill_buckets}
    assert len(eng._decode_exe) == len(eng.decode_buckets)
    assert len(eng._prefill_exe) == len(eng.prefill_buckets)
    with pytest.raises(EngineShapeError):
        eng.decode_bucket(5)          # > largest bucket
    with pytest.raises(EngineShapeError):
        eng.prefill_bucket(65)        # > largest prefill bucket
    with pytest.raises(EngineShapeError):
        eng._decode_fn(3)             # not a configured bucket


def test_closure_sim_covers_moe_engine(moe_engine):
    """The device-free scheduler replay (the check_program gate) over
    the MoE engine's bucket/pool config: every requested signature
    falls inside the engine's AOT sets."""
    eng = moe_engine
    used_d, used_p, ok_d, ok_p = simulate_decode_signatures(
        eng.decode_buckets, eng.prefill_buckets, eng.pool.page_size,
        eng.pool.num_pages, eng.max_seq_len, n_requests=100, seed=0)
    assert ok_d == eng.decode_signatures()
    assert ok_p == eng.prefill_signatures()
    assert used_d <= ok_d and used_p <= ok_p


def test_engine_status_surface(moe_engine):
    st = moe_engine.status()
    assert st["model"] == "ernie_moe"
    assert st["fused_moe_dispatch"] is True
    assert st["moe_layers"] == 1
    assert st["aot_programs"] == len(moe_engine._decode_exe) + \
        len(moe_engine._prefill_exe)
    assert st["pool"]["num_pages"] == moe_engine.pool.num_pages


def test_predicted_moe_serving_row_sane():
    from paddle_tpu.serving.predict import predicted_moe_serving_row
    row = predicted_moe_serving_row("tiny", concurrency=2, page_size=8)
    assert row["model"] == "ernie_moe"
    assert row["predicted_tokens_per_sec"] > 0
    assert row["predicted_bound"] in ("compute", "memory", "comm")
    assert row["moe_layers"] >= 1
    assert row["predicted_step_ms_unfused"] > 0
    assert row["predicted_fused_dispatch_speedup"] > 0


def test_predicted_fused_dispatch_row_beats_baseline():
    """The bench acceptance bar: the fused dispatch+combine stage beats
    the gather chain in the static cost model, the PTCS004 diagnostic
    fires on the old path and is clean on the new — all carried in the
    anchor row itself."""
    from paddle_tpu.serving.predict import predicted_fused_dispatch_row
    row = predicted_fused_dispatch_row()
    assert row["predicted_speedup"] > 1.0, row
    assert row["hbm_mb_fused"] < row["hbm_mb_unfused"]
    assert row["ptcs004_fires_unfused"] is True
    assert row["ptcs004_clean_fused"] is True


def test_bench_compare_maps_serving_moe_anchor():
    import os
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "tools"))
    import bench_compare
    rows = {
        "serving_moe_tokens_per_sec": {"metric":
                                       "serving_moe_tokens_per_sec",
                                       "value": 100.0, "unit": "tokens/s"},
        "serving_moe_predicted": {"metric": "serving_moe_predicted",
                                  "value": 900.0, "unit": "tokens/s"},
    }
    anchor = bench_compare._predicted_anchor(
        "serving_moe_tokens_per_sec", rows)
    assert anchor is rows["serving_moe_predicted"]
    # the CPU-smoke variant maps onto the same anchor
    anchor = bench_compare._predicted_anchor(
        "serving_moe_tokens_per_sec_cpu_smoke", rows)
    assert anchor is rows["serving_moe_predicted"]
