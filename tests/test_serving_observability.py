"""Serving observability: per-request traces (requests.jsonl + chrome
export), SLO guardrails (violation event + counter + flight dump naming
rids), the live /metrics //healthz //status endpoint, and the perf
doctor's serving gap attribution over the checked-in fixture.

Acceptance (ISSUE 10): an induced SLO violation in a real scheduler run
produces the violation event, the counter increment, and a flight dump
naming offending rids; /metrics and /status serve correct data under
concurrent scrapes mid-run; the doctor's serving buckets sum exactly to
the measured-vs-predicted per-token delta on the fixture."""
import json
import os
import shutil
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.observability import anomaly, doctor, flight
from paddle_tpu.observability import runlog
from paddle_tpu.observability.reqtrace import (RequestTrace,
                                               export_chrome_trace,
                                               fold_request_records)
from paddle_tpu.observability.slo import SLOConfig, SLOTracker
from paddle_tpu.serving import ContinuousBatchingScheduler, ServingEngine
from paddle_tpu.serving.scheduler import Request, _ShapeProbeEngine

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURE = os.path.join(REPO, "tests", "fixtures", "serving_doctor_run")


@pytest.fixture(autouse=True)
def _fresh_observability_state(tmp_path, monkeypatch):
    """Per-test isolation of the process-global recorder / monitors /
    run logger; a tmp run dir catches every stream."""
    monkeypatch.setenv("PADDLE_TELEMETRY_DIR", str(tmp_path / "run"))
    monkeypatch.setattr(runlog, "_run_logger", None)
    flight.reset_for_tests()
    anomaly.reset_monitors()
    yield
    logger = runlog._run_logger
    if logger is not None:
        logger.close()
    monkeypatch.setattr(runlog, "_run_logger", None)
    flight.reset_for_tests()
    anomaly.reset_monitors()


def _counter_value(name, **labels):
    from paddle_tpu.observability import get_registry
    inst = get_registry().get(name)
    if inst is None:
        return 0.0
    total = 0.0
    for lab, state in inst.collect():
        if all(lab.get(k) == v for k, v in labels.items()):
            total += state.get("value", state.get("count", 0.0))
    return total


def _probe_sched(max_queue=1024, slo=None, num_pages=40, max_seq_len=64):
    """Real scheduler over the device-free shape-probe engine."""
    eng = _ShapeProbeEngine(decode_buckets=(1, 2, 4),
                            prefill_buckets=(8, 64), page_size=8,
                            num_pages=num_pages, max_seq_len=max_seq_len)
    return ContinuousBatchingScheduler(eng, max_queue=max_queue, slo=slo)


@pytest.fixture(scope="module")
def tiny_engine():
    from paddle_tpu.models.gpt import (GPTForPretraining, GPTModel,
                                       gpt_tiny_config)
    paddle.seed(0)
    cfg = gpt_tiny_config()
    model = GPTForPretraining(GPTModel(cfg))
    eng = ServingEngine(model, page_size=8, decode_buckets=(1, 2),
                        aot=False)
    return eng, cfg


def _prompts(cfg, lens, seed=0):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, cfg.vocab_size, (s,)).astype(np.int32)
            for s in lens]


# ===========================================================================
# Request summary fixes + reject reasons (satellites 1-2)
# ===========================================================================

def test_request_summary_zero_clock_is_not_missing():
    """A monotonic clock reading 0.0 is a real timestamp; the old
    truthiness checks reported queue_wait/ttft as None for it."""
    r = Request(rid=0, prompt=np.zeros(4, np.int32), max_new_tokens=2,
                submit_time=0.0)
    r.admit_time = 0.0          # same-instant admission, legal
    r.first_token_time = 0.0
    r.finish_time = 0.5
    r.tokens = [1, 2]
    s = r.summary()
    assert s["queue_wait_s"] == 0.0
    assert s["ttft_s"] == 0.0
    assert s["decode_s"] == 0.5 and s["total_s"] == 0.5
    assert s["decode_tokens_per_sec"] == pytest.approx(2.0)
    assert s["reject_reason"] is None and s["slo_met"] is None


def test_reject_reasons_and_counter_labels():
    base = {r: _counter_value("paddle_serving_requests_total",
                              event="rejected", reason=r)
            for r in ("max_new<1", "too_long", "retry_after",
                      "pool_too_small")}
    sched = _probe_sched(num_pages=5, max_seq_len=64)
    cases = [
        (np.zeros(8, np.int32), 0, "max_new<1"),
        (np.zeros(60, np.int32), 10, "too_long"),
        (np.zeros(40, np.int32), 8, "pool_too_small"),  # 6 pages > 4
    ]
    for prompt, max_new, want in cases:
        r = sched.submit(prompt, max_new)
        assert r.state == "rejected" and r.reject_reason == want
        assert r.summary()["reject_reason"] == want
    full = _probe_sched(max_queue=0)
    r = full.submit(np.zeros(8, np.int32), 4)
    # cost-aware admission: the old binary queue_full is a priced
    # retry_after reject with a machine-readable backoff hint
    assert r.reject_reason == "retry_after"
    assert r.retry_after_s is not None and r.retry_after_s > 0
    for reason in ("max_new<1", "too_long", "retry_after",
                   "pool_too_small"):
        assert _counter_value("paddle_serving_requests_total",
                              event="rejected", reason=reason) \
            == base[reason] + 1
    # rejects are terminal records too
    assert len(sched.rejected) == 3
    assert {rec["reject_reason"] for rec in sched.request_records()} \
        == {"max_new<1", "too_long", "pool_too_small"}


def test_prefill_is_timed_and_reaches_flight_and_histogram(tiny_engine):
    """Satellite 1: prefill cost is no longer invisible — it lands in
    paddle_serving_prefill_seconds AND the flight recorder / anomaly
    path under path="serving_prefill"."""
    from paddle_tpu.observability import get_registry
    eng, cfg = tiny_engine
    hist = get_registry().histogram("paddle_serving_prefill_seconds")
    base = hist.count
    sched = ContinuousBatchingScheduler(eng)
    reqs = [sched.submit(p, max_new_tokens=3)
            for p in _prompts(cfg, (5, 9), seed=1)]
    sched.run()
    assert hist.count == base + 2
    for r in reqs:
        assert r.prefill_s is not None and r.prefill_s > 0
        assert r.summary()["prefill_s"] == r.prefill_s
    prefill_steps = [rec for rec in flight.get_flight_recorder().records()
                     if rec.get("kind") == "step"
                     and rec.get("path") == "serving_prefill"]
    assert len(prefill_steps) >= 2
    # decode step walltimes stay prefill-free (bench reads them as
    # per-token latencies)
    assert len(sched.step_times) == sched.steps


# ===========================================================================
# per-request traces: spans, requests.jsonl, chrome export
# ===========================================================================

def test_trace_spans_and_requests_jsonl_stream(tmp_path, tiny_engine):
    eng, cfg = tiny_engine
    run_dir = os.environ["PADDLE_TELEMETRY_DIR"]
    sched = ContinuousBatchingScheduler(eng)
    reqs = [sched.submit(p, max_new_tokens=4)
            for p in _prompts(cfg, (5, 11), seed=2)]
    sched.submit(np.zeros(200, np.int32), 4)      # rejected: too_long
    sched.run()
    for r in reqs:
        phases = [sp["phase"] for sp in r.trace.spans]
        assert phases == ["queued", "prefill", "decode"]
        assert len(r.trace.token_samples) == 3    # 4 tokens, 1st = prefill
    recs, bad = runlog._read_jsonl(os.path.join(run_dir, "requests.jsonl"))
    assert bad == 0 and len(recs) == 3
    by_state = {}
    for rec in recs:
        by_state.setdefault(rec["state"], []).append(rec)
    assert len(by_state["finished"]) == 2
    assert by_state["rejected"][0]["reject_reason"] == "too_long"
    fin = by_state["finished"][0]
    assert fin["queue_wait_s"] >= 0 and fin["ttft_s"] > 0
    assert fin["per_token_s"]["count"] == 3
    assert fin["spans"][0]["phase"] == "queued"
    # chrome export is readable by tools/trace_summary.py
    out = export_chrome_trace(run_dir, str(tmp_path / "req_trace.json"))
    import sys
    sys.path.insert(0, REPO)
    from tools.trace_summary import summarize
    text = "\n".join(summarize(out))
    for phase in ("queued", "prefill", "decode", "rejected"):
        assert phase in text
    doc = json.load(open(out))
    spans = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    assert {e["name"] for e in spans} \
        == {"queued", "prefill", "decode", "rejected"}
    assert all(e["dur"] >= 0 and e["ts"] >= 0 for e in spans)


def test_merge_run_dir_folds_per_request_percentiles(tmp_path):
    run_dir = str(tmp_path / "fold")
    os.makedirs(run_dir)
    with open(os.path.join(run_dir, "requests.jsonl"), "w") as f:
        for i in range(10):
            f.write(json.dumps({
                "event": "request", "rid": i, "state": "finished",
                "reject_reason": None, "prompt_len": 8, "new_tokens": 5,
                "queue_wait_s": 0.01 * i, "ttft_s": 0.05 + 0.01 * i,
                "prefill_s": 0.04, "decode_s": 0.08,
                "total_s": 0.13 + 0.01 * i, "slo_met": i < 8,
                "per_token_s": {"count": 4, "mean": 0.02, "p50": 0.02,
                                "p95": 0.02, "p99": 0.02, "max": 0.02},
            }) + "\n")
        f.write(json.dumps({"event": "request", "rid": 10,
                            "state": "rejected",
                            "reject_reason": "queue_full",
                            "new_tokens": 0}) + "\n")
        f.write('{"torn')
    summary = runlog.merge_run_dir(run_dir, write=False)
    sv = summary["serving"]
    assert summary["corrupt_lines"] == 1
    assert sv["requests"] == 11 and sv["finished"] == 10
    assert sv["reject_reasons"] == {"queue_full": 1}
    assert sv["new_tokens_total"] == 50
    assert sv["queue_wait_s"]["p50"] == pytest.approx(0.04)  # idx round(4.5)=4
    assert sv["ttft_s"]["max"] == pytest.approx(0.14)
    assert sv["per_token_s"]["p99"] == pytest.approx(0.02)
    assert sv["tokens"]["mean"] == 5.0
    assert sv["slo"] == {"met": 8, "missed": 2, "goodput_tokens": 40,
                         "goodput_fraction": 0.8}
    assert fold_request_records([]) is None


# ===========================================================================
# SLO guardrails
# ===========================================================================

def test_slo_violation_event_counter_and_flight_dump_name_rids(
        monkeypatch, tiny_engine):
    """ACCEPTANCE: an induced SLO violation in a real scheduler run
    produces the anomaly-style event, the counter increment, and a
    flight dump naming the offending rids."""
    monkeypatch.setattr(flight, "_SOFT_DUMP_MIN_INTERVAL_S", 0.0)
    eng, cfg = tiny_engine
    run_dir = os.environ["PADDLE_TELEMETRY_DIR"]
    base_v = _counter_value("paddle_serving_slo_violations_total",
                            slo="ttft_p95")
    base_a = _counter_value("paddle_anomalies_total", kind="slo_ttft_p95")
    sched = ContinuousBatchingScheduler(
        eng, slo={"ttft_p95_s": 1e-9, "min_requests": 2,
                  "cooldown_s": 0.0})
    reqs = [sched.submit(p, max_new_tokens=3)
            for p in _prompts(cfg, (5, 9, 7), seed=3)]
    sched.run()
    assert all(r.state == "finished" for r in reqs)
    # the impossible target means no request met SLO
    assert all(r.slo_met is False for r in reqs)
    assert _counter_value("paddle_serving_slo_violations_total",
                          slo="ttft_p95") > base_v
    assert _counter_value("paddle_anomalies_total",
                          kind="slo_ttft_p95") > base_a
    events, _ = runlog._read_jsonl(
        os.path.join(run_dir, "events.rank0.jsonl"))
    viol = [e for e in events if e.get("event") == "anomaly"
            and e.get("kind") == "slo_ttft_p95"]
    assert viol and viol[0]["target_s"] == pytest.approx(1e-9)
    assert viol[0]["offending_rids"]
    if sched.slo.last_dump_thread is not None:
        sched.slo.last_dump_thread.join(timeout=30)
    dump_path = os.path.join(run_dir, "flight.rank0.slo.json")
    assert os.path.exists(dump_path), "SLO violation must leave a black box"
    doc = json.load(open(dump_path))
    assert doc["slo"] == "ttft_p95"
    assert set(doc["offending_rids"]) <= {r.rid for r in reqs}
    assert doc["offending_rids"], "the dump must NAME the offending rids"
    # the finished records carry slo_met for goodput audits
    recs, _ = runlog._read_jsonl(os.path.join(run_dir, "requests.jsonl"))
    assert all(rec["slo_met"] is False for rec in recs)


def test_slo_goodput_and_burn_rate_accounting():
    base = _counter_value("paddle_serving_goodput_tokens_total")
    tracker = SLOTracker(SLOConfig(ttft_p95_s=1.0, min_requests=4,
                                   cooldown_s=0.0))
    for rid in range(8):
        assert tracker.observe_admission(rid, ttft_s=0.1,
                                         queue_wait_s=0.01) == []
        met = tracker.observe_request(
            {"rid": rid, "ttft_s": 0.1, "new_tokens": 10})
        assert met is True
    snap = tracker.snapshot()
    assert snap["goodput_tokens"] == 80 and snap["requests_met"] == 8
    assert snap["goodput_fraction"] == 1.0
    assert snap["burn_rates"]["ttft_p95"] == 0.0
    assert snap["violations"] == 0
    assert _counter_value("paddle_serving_goodput_tokens_total") \
        == base + 80
    # one outlier in 9 samples is 11% over target — past the 5% error
    # budget — and it fires at ADMISSION (the incident moment), before
    # the slow request ever finishes
    fired = tracker.observe_admission(99, ttft_s=5.0)
    assert [f["slo"] for f in fired] == ["ttft_p95"]
    assert fired[0]["offending_rids"] == [99]
    tracker.observe_request({"rid": 99, "ttft_s": 5.0, "new_tokens": 10})
    snap = tracker.snapshot()
    assert snap["requests_missed"] == 1
    assert snap["burn_rates"]["ttft_p95"] > 1.0
    assert snap["violations"] == 1
    assert snap["last_violation"]["offending_rids"] == [99]


def test_slo_per_token_window_fires_on_slow_ticks(monkeypatch):
    monkeypatch.setattr(flight, "_SOFT_DUMP_MIN_INTERVAL_S", 0.0)
    tracker = SLOTracker(SLOConfig(per_token_p99_s=0.01, min_tokens=8,
                                   cooldown_s=0.0))
    for _ in range(8):
        assert tracker.observe_tokens([0, 1], 0.001) == []
    fired = tracker.observe_tokens([2, 3], 0.5)
    assert [f["slo"] for f in fired] == ["per_token_p99"]
    assert set(fired[0]["offending_rids"]) == {2, 3}
    assert fired[0]["burn_rate"] > 1.0


def test_merge_slo_violations_from_events_when_counters_never_flushed(
        tmp_path):
    """A run killed before its next metrics flush still reports the SLO
    violations it logged synchronously — max(counter, events) per rank,
    same contract as the anomaly tallies."""
    run_dir = str(tmp_path / "crashed")
    os.makedirs(run_dir)
    with open(os.path.join(run_dir, "events.rank0.jsonl"), "w") as f:
        for _ in range(2):
            f.write(json.dumps({"ts": 1.0, "rank": 0, "generation": 0,
                                "event": "anomaly",
                                "kind": "slo_ttft_p95",
                                "slo": "ttft_p95",
                                "offending_rids": [3]}) + "\n")
    summary = runlog.merge_run_dir(run_dir, write=False)
    assert summary["serving"]["slo_violations"] == {"ttft_p95": 2}
    # with the counter ALSO flushed for the same firings: no double count
    with open(os.path.join(run_dir, "metrics.rank0.gen0.jsonl"), "w") as f:
        f.write(json.dumps({
            "name": "paddle_serving_slo_violations_total",
            "type": "counter", "labels": {"slo": "ttft_p95"}, "value": 2,
            "rank": 0, "generation": 0}) + "\n")
    summary = runlog.merge_run_dir(run_dir, write=False)
    assert summary["serving"]["slo_violations"] == {"ttft_p95": 2}


def test_scheduler_bounds_retained_terminal_requests():
    sched = _probe_sched(num_pages=400, max_seq_len=64)
    sched.max_retained = 5
    for _ in range(12):
        sched.submit(np.zeros(8, np.int32), 2)
        sched.run()
    assert len(sched.finished) == 5
    full = _probe_sched(max_queue=0)
    full.max_retained = 3
    for _ in range(9):
        full.submit(np.zeros(8, np.int32), 2)
    assert len(full.rejected) == 3


# ===========================================================================
# HTTP endpoint: /metrics, /status, /healthz, shutdown
# ===========================================================================

def _get(url, timeout=10):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.status, resp.read().decode()


def test_http_concurrent_metrics_scrapes_mid_run():
    """ACCEPTANCE: concurrent /metrics scrapes during an active
    scheduler run return consistent text expo."""
    sched = _probe_sched(num_pages=200, max_seq_len=64)
    srv = sched.serve_http()
    try:
        rng = np.random.default_rng(0)
        for _ in range(40):
            sched.submit(np.zeros(int(rng.integers(1, 40)), np.int32),
                         int(rng.integers(1, 8)))
        results, errors = [], []

        def scrape():
            try:
                for _ in range(10):
                    code, body = _get(srv.url + "/metrics")
                    results.append((code, body))
            except Exception as e:  # noqa: BLE001
                errors.append(repr(e))

        threads = [threading.Thread(target=scrape) for _ in range(4)]
        for t in threads:
            t.start()
        while sched.pending:
            sched.step()
        for t in threads:
            t.join(timeout=30)
        assert not errors, errors
        assert len(results) == 40
        for code, body in results:
            assert code == 200
            # parseable, consistent expo: every sample line is
            # "name{labels} value" with a float value
            for line in body.splitlines():
                if not line or line.startswith("#"):
                    continue
                float(line.rsplit(" ", 1)[1])
            assert "paddle_serving_requests_total" in body
    finally:
        srv.close()


def test_http_status_matches_scheduler_and_pool_state(tiny_engine):
    eng, cfg = tiny_engine
    sched = ContinuousBatchingScheduler(
        eng, slo={"ttft_p95_s": 60.0, "per_token_p99_s": 60.0})
    srv = sched.serve_http()
    try:
        reqs = [sched.submit(p, max_new_tokens=3)
                for p in _prompts(cfg, (5, 9), seed=4)]
        sched.submit(np.zeros(300, np.int32), 4)   # rejected
        sched.run()
        code, body = _get(srv.url + "/status")
        assert code == 200
        st = json.loads(body)
        assert st["healthy"] is True and st["last_error"] is None
        assert st["queue_depth"] == 0 and st["running"] == 0
        assert st["finished"] == len(sched.finished) == 2
        assert st["rejected"] == 1
        assert st["steps"] == sched.steps
        assert st["kv_pool"] == eng.pool.stats()
        assert st["kv_pool"]["pages_in_use"] == 0
        assert "internal_fragmentation" in st["kv_pool"]
        assert st["engine"]["decode_buckets"] == [1, 2]
        assert st["slo"]["targets_s"] == {"ttft_p95": 60.0,
                                          "per_token_p99": 60.0}
        assert st["slo"]["goodput_tokens"] == \
            sum(len(r.tokens) for r in reqs)
        code, body = _get(srv.url + "/healthz")
        assert code == 200 and body.strip() == "ok"
        code, _ = _get(srv.url + "/metrics")
        assert code == 200
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(srv.url + "/nope")
        assert ei.value.code == 404
    finally:
        srv.close()


def test_http_healthz_flips_unhealthy_on_engine_failure(monkeypatch):
    sched = _probe_sched()
    srv = sched.serve_http()
    try:
        sched.submit(np.zeros(8, np.int32), 4)

        def boom(seq_ids, bucket):
            raise RuntimeError("injected engine failure")

        monkeypatch.setattr(sched.engine, "decode", boom)
        with pytest.raises(RuntimeError, match="injected"):
            sched.step()
        assert sched.healthy is False
        assert "injected engine failure" in sched.last_error
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(srv.url + "/healthz")
        assert ei.value.code == 503
        assert "injected engine failure" in ei.value.read().decode()
        # /status still serves, and says why
        st = json.loads(_get(srv.url + "/status")[1])
        assert st["healthy"] is False
        assert "injected" in st["last_error"]
    finally:
        srv.close()


def test_http_clean_shutdown_no_leaked_thread_or_socket():
    sched = _probe_sched()
    srv = sched.serve_http()
    url, port = srv.url, srv.port
    assert _get(url + "/healthz")[0] == 200
    thread = srv._thread
    srv.close()
    srv.close()                      # idempotent
    assert not thread.is_alive()
    with pytest.raises((urllib.error.URLError, ConnectionError, OSError)):
        urllib.request.urlopen(url + "/healthz", timeout=2)
    # the port is actually free again: a new server can bind it
    from paddle_tpu.observability.httpd import ServingStatusServer
    srv2 = ServingStatusServer(port=port)
    try:
        assert _get(srv2.url + "/metrics")[0] == 200
    finally:
        srv2.close()


# ===========================================================================
# perf doctor: serving gap attribution
# ===========================================================================

def test_serving_attribution_buckets_sum_exactly():
    summary = {
        "serving": {"finished": 8, "requests": 9, "rejected": 1,
                    "new_tokens_total": 512,
                    "request_seconds_total": 10.24,   # 20 ms/token
                    "queue_wait_seconds_total": 1.024,
                    "prefill_seconds_total": 0.512,
                    "per_token_s": {"p50": 0.012, "p95": 0.014}},
        "compile": {"count": 1, "seconds": 2.56},
    }
    pred = {"predicted_decode_step_ms": 9.0,
            "predicted_per_token_ms_p50": 9.0,
            "predicted_per_token_ms_p95": 9.5,
            "predicted_tokens_per_sec": 888.9}
    attr = doctor.attribute_serving_gap(summary, pred)
    assert attr["measured_ms"] == pytest.approx(25.0)    # +compile 5ms
    assert attr["predicted_ms"] == 9.0
    assert sum(attr["buckets"].values()) == pytest.approx(
        attr["delta_ms"], abs=0.01)
    assert attr["buckets"]["queue"] == pytest.approx(2.0)
    assert attr["buckets"]["prefill"] == pytest.approx(1.0)
    assert attr["buckets"]["compile"] == pytest.approx(5.0)
    assert attr["buckets"]["decode"] == pytest.approx(
        attr["delta_ms"] - 8.0, abs=0.01)
    assert attr["per_token_ms"]["p50"]["measured"] == 12.0
    assert attr["per_token_ms"]["p95"]["ratio"] == pytest.approx(
        14.0 / 9.5, abs=0.01)
    # missing inputs degrade to None, never raise
    assert doctor.attribute_serving_gap({}, pred) is None
    assert doctor.attribute_serving_gap(summary, None) is None
    assert doctor.attribute_serving_gap(summary, {"other": 1}) is None


def test_doctor_serving_fixture_buckets_sum_and_findings(tmp_path):
    """ACCEPTANCE: on the checked-in serving fixture the doctor's
    queue/prefill/compile/decode buckets sum exactly to the measured-vs-
    predicted per-token delta; SLO violation + reject findings rank."""
    run_dir = str(tmp_path / "run")
    shutil.copytree(FIXTURE, run_dir)
    report = doctor.diagnose_run_dir(run_dir)
    sattr = report["serving_attribution"]
    assert sattr is not None
    assert sum(sattr["buckets"].values()) == pytest.approx(
        sattr["delta_ms"], abs=0.01)
    assert set(sattr["buckets"]) == {"queue", "prefill", "compile",
                                     "decode"}
    assert sattr["tokens"] == 512 and sattr["requests"] == 8
    # compile dominates this fixture (22.4s AOT builds over 512 tokens)
    assert max(sattr["buckets"], key=lambda k: sattr["buckets"][k]) \
        == "compile"
    kinds = {f["kind"]: f for f in report["findings"]}
    assert "slo_violations" in kinds
    assert "ttft_p95 x1" in kinds["slo_violations"]["detail"]
    assert "rejected_requests" in kinds
    assert "serving_slower_than_roofline" in kinds
    assert "goodput" in kinds          # 320/512 tokens = 62.5% < 95%
    assert "62.5%" in kinds["goodput"]["detail"]
    text = doctor.format_report(report)
    assert "serving gap attribution" in text
    assert "ms/output-token" in text and "goodput" in text
    sv = report["summary"]["serving"]
    assert sv["slo_violations"] == {"ttft_p95": 1}
    assert sv["slo"]["goodput_tokens"] == 320


def test_perf_doctor_cli_serving_fixture_gate(tmp_path, capsys):
    """Tier-1 gate: `tools/perf_doctor.py <fixture> --no-write` exits 0,
    prints the serving section, and leaves the fixture untouched."""
    from tools.perf_doctor import main as doctor_main
    assert doctor_main([FIXTURE, "--no-write"]) == 0
    out = capsys.readouterr().out
    assert "serving gap attribution" in out
    assert "slo_violations" in out
    assert not os.path.exists(os.path.join(FIXTURE, "run_summary.json"))
    # --json carries the serving attribution machine-readably
    assert doctor_main([FIXTURE, "--no-write", "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["serving_attribution"]["tokens"] == 512
    assert doc["summary"]["serving"]["finished"] == 8


def test_scheduler_status_without_http(tiny_engine):
    """status() is usable directly (no server) and safe mid-lifecycle."""
    eng, cfg = tiny_engine
    sched = ContinuousBatchingScheduler(eng)
    st = sched.status()
    assert st["healthy"] and st["queue_depth"] == 0
    assert st["finished"] == 0 and st["slo"] is None
    (p,) = _prompts(cfg, (6,), seed=5)
    sched.submit(p, max_new_tokens=2)
    st = sched.status()
    assert st["queue_depth"] == 1
    sched.run()
    st = sched.status()
    assert st["finished"] == 1 and st["kv_pool"]["pages_in_use"] == 0
    assert st["engine"]["aot_programs"] == 0     # aot=False engine
    assert st["uptime_s"] >= 0
