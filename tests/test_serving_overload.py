"""Overload control & graceful degradation (ISSUE 19).

Coverage:

- request deadlines: expiry cancels wherever the request lives
  (queued / mid-prefill / mid-decode) through ONE terminal path, with
  exact page reclamation (zero leaked pages) and its own terminal
  state + counter; explicit ``cancel(rid)`` takes the same path;
- cost-aware admission: the old binary ``queue_full`` is gone — a
  capacity reject is priced against the observed drain rate and
  carries a machine-readable ``retry_after_s`` (env-cappable);
- brownout state machine: ``healthy → brownout → shedding`` on SLO
  burn rates with hysteretic exits; brownout halves completion
  budgets, prefers cache hits at admission, pauses background hooks;
  shedding rejects cache-miss traffic with ``shed`` + retry hint;
- SLO / folding / doctor: ``deadline_exceeded`` and priced rejects
  are their OWN terminal outcomes (never goodput), degraded decode
  time becomes the doctor's ``degraded`` bucket and the buckets still
  sum EXACTLY; the checked-in fleet fixture gates it at rc=0;
- router circuit breaker: consecutive RPC failures open it, routing
  skips the replica, the supervision poll is the half-open probe;
- ChaosProxy: deterministic seeded fault schedule, scripted fault
  behaviors (drop / delay / duplicate / truncate / bitflip);
- ACCEPTANCE: a real 2-replica fleet behind ChaosProxy (seeded drops
  + delays + one corrupted migration chunk) with deadlines on every
  request — every request reaches a terminal state, zero hangs, zero
  leaked KV pages, breaker open/close observed;
- a slow-marked chaos loop combining proxy faults with SIGSTOP /
  SIGKILL process faults.
"""
import json
import os
import socket
import threading
import time
import types

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.distributed.fleet.elastic.fault_injection import ChaosProxy
from paddle_tpu.models.gpt import gpt_tiny_config
from paddle_tpu.serving.scheduler import (ContinuousBatchingScheduler,
                                          _ShapeProbeEngine)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURE = os.path.join(REPO, "tests", "fixtures", "fleet_doctor_run")


def _probe_sched(max_queue=1024, slo=None, num_pages=40, max_seq_len=64,
                 prefill_chunk=None, **kw):
    eng = _ShapeProbeEngine(decode_buckets=(1, 2, 4),
                            prefill_buckets=(8, 64), page_size=8,
                            num_pages=num_pages, max_seq_len=max_seq_len,
                            prefill_chunk=prefill_chunk)
    return ContinuousBatchingScheduler(eng, max_queue=max_queue, slo=slo,
                                       **kw)


class _FakeSLO:
    """Controllable burn-rate source with the tracker surface the
    scheduler touches."""

    def __init__(self, burn=0.0):
        self.burn = burn
        self.terminal_states = []

    def burn_rates(self):
        return {"ttft_p95_s": self.burn}

    def observe_request(self, summary):
        self.terminal_states.append(summary.get("state"))
        return summary.get("state") == "finished"

    def observe_admission(self, *a, **kw):
        pass

    def observe_tokens(self, *a, **kw):
        pass

    def snapshot(self):
        return {"burn_rates": self.burn_rates()}


# ===========================================================================
# deadlines: expiry + explicit cancel, exact page reclamation
# ===========================================================================

def test_deadline_expires_queued_request():
    sched = _probe_sched()
    free0 = sched.engine.pool.free_pages
    r = sched.submit(np.zeros(8, np.int32), 4, deadline_s=0.005)
    assert r.deadline_s == 0.005
    time.sleep(0.02)
    sched.step()
    assert r.state == "deadline_exceeded"
    assert r.finish_time is not None
    assert sched.engine.pool.free_pages == free0
    assert sched._reserved_pages == 0
    assert sched.deadline_cancelled == 1
    assert sched.status()["deadline_exceeded"] == 1
    # the terminal record reaches request_records() like any other
    recs = sched.request_records()
    assert recs[-1]["state"] == "deadline_exceeded"


def test_deadline_expires_running_request_and_reclaims_pages():
    sched = _probe_sched()
    pool = sched.engine.pool
    free0 = pool.free_pages
    r = sched.submit(np.zeros(8, np.int32), 30, deadline_s=0.03)
    sched.step()                               # admit + prefill + decode
    assert r.state == "running" and len(r.tokens) >= 1
    time.sleep(0.05)
    sched.step()                               # sweep cancels mid-decode
    assert r.state == "deadline_exceeded"
    assert pool.free_pages == free0            # zero leaked pages
    assert sched._reserved_pages == 0
    assert not sched.pending
    # the span records where the cancel landed and the wasted tokens
    last = r.trace.spans[-1]
    assert last["phase"] == "deadline_exceeded"
    assert last["cancelled_in"] == "running"


def test_deadline_expires_mid_prefill_chunked():
    sched = _probe_sched(prefill_chunk=8, prefill_token_budget=8)
    pool = sched.engine.pool
    free0 = pool.free_pages
    r = sched.submit(np.zeros(40, np.int32), 4, deadline_s=0.03)
    sched.step()                               # one 8-token chunk of 40
    assert r.state == "prefilling"
    time.sleep(0.05)
    sched.step()
    assert r.state == "deadline_exceeded"
    assert r.trace.spans[-1]["cancelled_in"] == "prefilling"
    assert pool.free_pages == free0
    assert sched._reserved_pages == 0


def test_default_deadline_env_knob(monkeypatch):
    monkeypatch.setenv("PADDLE_FLEET_DEADLINE_DEFAULT_S", "2.5")
    sched = _probe_sched()
    r = sched.submit(np.zeros(8, np.int32), 2)
    assert r.deadline_s == 2.5
    # explicit deadline wins over the default
    r2 = sched.submit(np.zeros(8, np.int32), 2, deadline_s=9.0)
    assert r2.deadline_s == 9.0


def test_explicit_cancel_in_each_phase_and_unknown_rid():
    sched = _probe_sched(prefill_chunk=8, prefill_token_budget=8)
    pool = sched.engine.pool
    free0 = pool.free_pages
    rq = sched.submit(np.zeros(8, np.int32), 4)     # stays queued
    assert sched.cancel(rq.rid) is True
    assert rq.state == "deadline_exceeded"
    rp = sched.submit(np.zeros(40, np.int32), 4)
    sched.step()                                    # first chunk only
    assert rp.state == "prefilling"
    assert sched.cancel(rp.rid) is True
    rr = sched.submit(np.zeros(8, np.int32), 30)
    sched.step()
    sched.step()
    assert rr.state == "running"
    assert sched.cancel(rr.rid) is True
    assert pool.free_pages == free0
    assert sched._reserved_pages == 0
    # unknown / already-terminal rids refuse
    assert sched.cancel(99999) is False
    assert sched.cancel(rr.rid) is False
    assert sched.deadline_cancelled == 3


# ===========================================================================
# cost-aware admission: priced retry_after replaces queue_full
# ===========================================================================

def test_full_queue_reject_is_priced_retry_after():
    sched = _probe_sched(max_queue=0)
    r = sched.submit(np.zeros(8, np.int32), 4)
    assert r.state == "rejected" and r.reject_reason == "retry_after"
    assert isinstance(r.retry_after_s, float)
    assert 0.05 <= r.retry_after_s <= 30.0
    s = r.summary()
    assert s["reject_reason"] == "retry_after"
    assert s["retry_after_s"] == pytest.approx(r.retry_after_s, abs=1e-3)
    ov = sched.status()["overload"]
    assert ov["retry_after_s"] > 0
    assert "drain_rate_rps" in ov["admission_cost"]


def test_retry_after_tracks_observed_drain_rate():
    sched = _probe_sched()
    for i in range(5):
        sched.submit(np.zeros(8, np.int32), 2)      # backlog of 5
    t0 = time.perf_counter()
    sched._finish_ts.extend(t0 + 0.1 * i for i in range(5))
    # 4 completions over 0.4s -> 10 rps; 5 queued -> ~0.5s to drain
    assert sched._drain_rate() == pytest.approx(10.0, rel=0.01)
    assert sched._retry_after_estimate() == pytest.approx(0.5, abs=0.01)
    # an SLO burning its budget scales the hint up
    sched.slo = _FakeSLO(burn=3.0)
    assert sched._retry_after_estimate() == pytest.approx(1.5, abs=0.05)


def test_retry_after_cap_env_knob(monkeypatch):
    monkeypatch.setenv("PADDLE_FLEET_RETRY_AFTER_CAP_S", "0.25")
    sched = _probe_sched(max_queue=0)
    r = sched.submit(np.zeros(8, np.int32), 4)
    # no drain history: the estimate saturates at the cap, not at 30s
    assert r.retry_after_s == pytest.approx(0.25)


# ===========================================================================
# brownout state machine
# ===========================================================================

def test_brownout_mode_machine_with_hysteresis():
    sched = _probe_sched()
    fake = _FakeSLO(0.0)
    sched.slo = fake
    sched.step()
    assert sched.mode == "healthy"
    fake.burn = 1.0                     # at the brownout line
    sched.step()
    assert sched.mode == "brownout" and sched.mode_transitions == 1
    fake.burn = 0.8                     # above the 0.5 exit: holds
    sched.step()
    assert sched.mode == "brownout"
    fake.burn = 2.0                     # 2x: shedding
    sched.step()
    assert sched.mode == "shedding"
    fake.burn = 1.5                     # above brownout entry: holds
    sched.step()
    assert sched.mode == "shedding"
    fake.burn = 0.9                     # below entry: back to brownout
    sched.step()
    assert sched.mode == "brownout"
    fake.burn = 0.4                     # below half: healthy again
    sched.step()
    assert sched.mode == "healthy" and sched.mode_transitions == 4
    ms = sched.status()["overload"]["mode_seconds"]
    assert set(ms) == {"healthy", "brownout", "shedding"}


def test_brownout_burn_env_knob(monkeypatch):
    monkeypatch.setenv("PADDLE_FLEET_BROWNOUT_BURN", "3.0")
    sched = _probe_sched()
    sched.slo = _FakeSLO(2.0)
    sched.step()
    assert sched.mode == "healthy"      # 2.0 < the raised threshold
    sched.slo.burn = 3.5
    sched.step()
    assert sched.mode == "brownout"


def test_brownout_clamps_completion_budget_and_tracks_degraded_time():
    sched = _probe_sched()
    sched.slo = _FakeSLO(1.0)           # held in brownout throughout
    r = sched.submit(np.zeros(8, np.int32), 8)
    sched.run()
    assert r.state == "finished"
    assert len(r.tokens) == 4           # (8+1)//2: halved, floor 1
    assert sched.degraded_s_total > 0
    assert r.summary()["degraded_s"] > 0


def test_brownout_prefers_cache_hits_and_pauses_background():
    sched = _probe_sched()
    sched.max_concurrency = 1
    hits = types.SimpleNamespace(
        match=lambda prompt: (None, None, 8 if prompt[0] == 7 else 0))
    sched.engine.prefix_cache = hits
    calls = []
    sched.background_hooks.append(lambda: calls.append(1))
    sched.slo = _FakeSLO(1.0)           # brownout
    miss = sched.submit(np.zeros(8, np.int32), 2)
    hit = sched.submit(np.full(8, 7, np.int32), 2)
    sched.step()
    # the cached-prefix request jumped the (older) miss
    assert hit.state in ("running", "finished")
    assert miss.state == "queued"
    assert calls == []                  # background paused off-healthy
    sched.slo.burn = 0.0
    sched.run()
    assert miss.state == "finished"
    assert calls                        # resumed once healthy


def test_shedding_rejects_cache_misses_with_retry_hint():
    sched = _probe_sched()
    sched.slo = _FakeSLO(2.5)
    sched.step()                        # drive the mode machine
    assert sched.mode == "shedding"
    r = sched.submit(np.zeros(8, np.int32), 4)
    assert r.state == "rejected" and r.reject_reason == "shed"
    assert r.retry_after_s is not None
    # cache hits still get in: shedding protects goodput, not uptime
    sched.engine.prefix_cache = types.SimpleNamespace(
        match=lambda prompt: (None, None, 8))
    r2 = sched.submit(np.zeros(8, np.int32), 4)
    assert r2.state == "queued"


# ===========================================================================
# SLO / folding / doctor terminal accounting
# ===========================================================================

def test_slo_tracker_counts_new_terminal_outcomes_outside_goodput():
    from paddle_tpu.observability.slo import SLOConfig, SLOTracker
    t = SLOTracker(SLOConfig())
    assert t.observe_request({"state": "deadline_exceeded",
                              "new_tokens": 5}) is False
    assert t.observe_request({"state": "rejected", "new_tokens": 0,
                              "retry_after_s": 1.5}) is False
    snap = t.snapshot()
    assert snap["requests_deadline_exceeded"] == 1
    assert snap["requests_rejected"] == 1
    # wasted tokens count toward total, never toward goodput
    assert snap["total_tokens"] == 5
    assert snap["goodput_tokens"] == 0
    assert snap["requests_met"] == 0 and snap["requests_missed"] == 0


def test_fold_request_records_new_outcomes():
    from paddle_tpu.observability.reqtrace import fold_request_records
    recs = [
        {"event": "request", "state": "finished", "new_tokens": 8,
         "degraded_s": 0.2},
        {"event": "request", "state": "deadline_exceeded",
         "new_tokens": 3, "degraded_s": 0.1},
        {"event": "request", "state": "rejected",
         "reject_reason": "retry_after", "retry_after_s": 1.5,
         "new_tokens": 0},
    ]
    sv = fold_request_records(recs)
    assert sv["deadline_exceeded"] == 1
    assert sv["deadline_exceeded_tokens_total"] == 3
    assert sv["degraded_seconds_total"] == pytest.approx(0.3)
    assert sv["retry_after_s"]["count"] == 1
    assert sv["retry_after_s"]["p50"] == pytest.approx(1.5)
    assert sv["reject_reasons"] == {"retry_after": 1}


def test_doctor_degraded_bucket_sums_exactly():
    from paddle_tpu.observability.doctor import attribute_serving_gap
    sv = {"new_tokens_total": 100, "request_seconds_total": 2.0,
          "queue_wait_seconds_total": 0.1,
          "prefill_seconds_total": 0.2,
          "degraded_seconds_total": 0.35,
          "per_token_s": {"p50": 0.02}}
    attr = attribute_serving_gap({"serving": sv},
                                 {"predicted_per_token_ms_p50": 5.0})
    assert "degraded" in attr["buckets"]
    assert attr["buckets"]["degraded"] == pytest.approx(3.5)
    assert sum(attr["buckets"].values()) == pytest.approx(
        attr["delta_ms"], abs=1e-9)
    # without degraded time the bucket never appears
    sv2 = dict(sv, degraded_seconds_total=0.0)
    attr2 = attribute_serving_gap({"serving": sv2},
                                  {"predicted_per_token_ms_p50": 5.0})
    assert "degraded" not in attr2["buckets"]
    assert sum(attr2["buckets"].values()) == pytest.approx(
        attr2["delta_ms"], abs=1e-9)


def test_perf_doctor_cli_fixture_gates_overload_buckets(capsys):
    """The checked-in fleet fixture now carries deadline_exceeded +
    degraded-time records; the CLI gate stays rc=0 and surfaces both
    as findings without writing into the fixture."""
    from tools.perf_doctor import main as doctor_main
    assert doctor_main([FIXTURE, "--no-write"]) == 0
    out = capsys.readouterr().out
    assert "deadline" in out
    assert "degraded" in out
    assert not os.path.exists(os.path.join(FIXTURE, "run_summary.json"))
    assert doctor_main([FIXTURE, "--no-write", "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    sattr = doc["serving_attribution"]
    assert "degraded" in sattr["buckets"]
    assert sum(sattr["buckets"].values()) == pytest.approx(
        sattr["delta_ms"], abs=0.01)
    assert doc["summary"]["serving"]["deadline_exceeded"] == 2
    kinds = {f["kind"] for f in doc["findings"]}
    assert "deadline_exceeded" in kinds


# ===========================================================================
# closure: cancellation replay adds zero program signatures
# ===========================================================================

def test_cancellation_mix_closure_no_new_signatures():
    from paddle_tpu.serving.scheduler import simulate_decode_signatures
    base_d, base_p, ok_d, ok_p = simulate_decode_signatures(
        (1, 2, 4), (8, 64), 8, 64, 64, n_requests=120, seed=0)
    cd, cp, okd_c, okp_c = simulate_decode_signatures(
        (1, 2, 4), (8, 64), 8, 64, 64, n_requests=120, seed=0,
        cancel_p=0.3)
    assert (okd_c, okp_c) == (ok_d, ok_p)
    assert cd <= ok_d and cp <= ok_p    # cancel = evict, no recompile
    # cancel_p=0 replays stay byte-identical to the golden stream
    again_d, again_p, _, _ = simulate_decode_signatures(
        (1, 2, 4), (8, 64), 8, 64, 64, n_requests=120, seed=0)
    assert (again_d, again_p) == (base_d, base_p)


# ===========================================================================
# router circuit breaker (unit: no processes)
# ===========================================================================

def test_breaker_opens_after_consecutive_failures_and_closes(
        tmp_path, monkeypatch):
    from paddle_tpu.serving.fleet import FleetRouter
    fr = FleetRouter(gpt_tiny_config(), n_replicas=2,
                     run_dir=str(tmp_path / "run"))
    h = types.SimpleNamespace(replica_id=0, rpc_failures=0,
                              breaker_open=False)
    fr._breaker_failure(h, op="submit")
    fr._breaker_failure(h, op="submit")
    assert not h.breaker_open           # below the default of 3
    fr._breaker_failure(h, op="submit")
    assert h.breaker_open
    assert [e["event"] for e in fr.breaker_events] == ["open"]
    # a success mid-streak resets the consecutive count
    fr._breaker_success(h)
    assert not h.breaker_open and h.rpc_failures == 0
    assert [e["event"] for e in fr.breaker_events] == ["open", "close"]
    # env knob: a single failure can open it
    monkeypatch.setenv("PADDLE_FLEET_BREAKER_FAILS", "1")
    fr._breaker_failure(h, op="poll")
    assert h.breaker_open
    ev = fr.breaker_events[-1]
    assert ev["event"] == "open" and ev["op"] == "poll"


def test_breaker_open_replica_is_not_routable(tmp_path):
    from paddle_tpu.serving.fleet import FleetRouter
    fr = FleetRouter(gpt_tiny_config(), n_replicas=2,
                     run_dir=str(tmp_path / "run"))

    def handle(rid, open_):
        return types.SimpleNamespace(
            replica_id=rid, rpc_failures=0, breaker_open=open_,
            retired=False, draining=False, poll_failures=0,
            alive=lambda: True,
            last_status={"healthy": True, "queue_depth": 0,
                         "kv_pool": {"free_pages": 10, "num_pages": 16}})
    fr.replicas = {0: handle(0, False), 1: handle(1, True)}
    snaps = fr._snapshots()
    assert snaps[0]["healthy"] is True
    assert snaps[1]["healthy"] is False


# ===========================================================================
# ChaosProxy (unit, against a local echo server)
# ===========================================================================

class _EchoServer:
    """One-line-in, one-line-out TCP echo upstream."""

    def __init__(self):
        self._srv = socket.create_server(("127.0.0.1", 0))
        self._srv.settimeout(0.25)
        self.addr = self._srv.getsockname()
        self.payloads = []
        self._closed = False
        self._t = threading.Thread(target=self._serve, daemon=True)
        self._t.start()

    def _serve(self):
        while not self._closed:
            try:
                conn, _ = self._srv.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            threading.Thread(target=self._one, args=(conn,),
                             daemon=True).start()

    def _one(self, conn):
        try:
            with conn, conn.makefile("rwb") as f:
                line = f.readline()
                if line:
                    self.payloads.append(line)
                    f.write(line)
                    f.flush()
                    time.sleep(0.05)   # hold briefly so replies split
        except OSError:
            pass

    def close(self):
        self._closed = True
        self._srv.close()


def _roundtrip(addr, payload=b"hello chaos proxy roundtrip\n",
               timeout=5.0):
    """Client view of one proxied exchange. A dropped connection may
    surface as clean EOF or a reset depending on timing — both mean
    "dead peer, no reply", which is what the RPC layer sees too."""
    chunks = []
    try:
        with socket.create_connection(addr, timeout=timeout) as s:
            s.sendall(payload)
            s.settimeout(timeout)
            while True:
                d = s.recv(65536)
                if not d:
                    break
                chunks.append(d)
    except (socket.timeout, OSError):
        pass
    return b"".join(chunks)


def test_chaos_proxy_schedule_is_deterministic_in_seed():
    echo = _EchoServer()
    seqs = []
    for _ in range(2):
        with ChaosProxy(echo.addr, seed=5, drop_p=0.3, delay_p=0.3,
                        delay_s=0.01) as proxy:
            for _ in range(12):
                _roundtrip(proxy.addr, timeout=3.0)
            seqs.append(list(proxy.faults))
    echo.close()
    assert seqs[0] == seqs[1]
    assert len(seqs[0]) == 12
    drawn = {f for _, f in seqs[0]}
    assert "drop" in drawn or "delay" in drawn


def test_chaos_proxy_scripted_faults_behave():
    echo = _EchoServer()
    payload = b"0123456789abcdef0123456789abcdef\n"
    with ChaosProxy(echo.addr, seed=0, delay_s=0.2,
                    schedule=["ok", "delay", "duplicate", "truncate",
                              "bitflip", "drop"]) as proxy:
        assert _roundtrip(proxy.addr, payload) == payload
        t0 = time.monotonic()
        assert _roundtrip(proxy.addr, payload) == payload
        assert time.monotonic() - t0 >= 0.2            # delayed reply
        assert _roundtrip(proxy.addr, payload) == payload * 2
        got = _roundtrip(proxy.addr, payload)
        assert 0 < len(got) < len(payload)             # torn reply
        upstream_before = len(echo.payloads)
        got = _roundtrip(proxy.addr, payload)
        corrupted = echo.payloads[upstream_before]
        assert corrupted != payload                    # one bit flipped
        assert len(corrupted) == len(payload)
        assert sum(a != b for a, b in zip(corrupted, payload)) == 1
        assert _roundtrip(proxy.addr, payload, timeout=3.0) == b""
        assert [f for _, f in proxy.faults] == [
            "ok", "delay", "duplicate", "truncate", "bitflip", "drop"]
        assert proxy.fault_counts()["ok"] == 1
    echo.close()


# ===========================================================================
# ACCEPTANCE: chaos fleet — every request terminal, zero hangs,
# zero leaked pages, breaker observed, corrupted migration refused
# ===========================================================================

def _drain_env(monkeypatch):
    monkeypatch.delenv("PADDLE_TELEMETRY_DIR", raising=False)
    monkeypatch.delenv("PADDLE_REQUESTS_PER_RANK", raising=False)


def _fleet_cfg():
    return gpt_tiny_config(num_layers=2, hidden_size=32, num_heads=2,
                           max_position_embeddings=128)


CHAOS_ENGINE_KW = dict(page_size=8, decode_buckets=(1, 2, 4, 8),
                       prefill_chunk=8, prefix_cache=False)

TERMINAL = {"finished", "rejected", "deadline_exceeded"}


def test_chaos_fleet_acceptance(tmp_path, monkeypatch):
    """ACCEPTANCE (ISSUE 19): 2 replicas behind seeded ChaosProxies
    (drops + delays on the control plane, one scripted corrupted
    migration chunk), a deadline on EVERY request. Every request
    reaches a terminal state, nothing hangs, the KV pools drain to
    zero pages in use, and the breaker opens and closes."""
    from paddle_tpu.observability import lockwitness
    from paddle_tpu.serving.fleet import FleetRouter, _rpc_request
    _drain_env(monkeypatch)
    monkeypatch.setenv("PADDLE_FLEET_BREAKER_FAILS", "1")
    # ISSUE 20: the whole chaos scenario runs under the runtime lock
    # witness — at the end the witnessed lock-order graph must be
    # acyclic (the runtime complement of the PTCY001 static check).
    # The env must be set BEFORE the router exists so its named locks
    # construct as witnessed.
    monkeypatch.setenv("PADDLE_LOCK_WITNESS", "1")
    lockwitness.reset()
    cfg = _fleet_cfg()
    fleet = FleetRouter(cfg, n_replicas=2,
                        engine_kwargs=dict(CHAOS_ENGINE_KW),
                        run_dir=str(tmp_path / "run"), seed=0,
                        max_restarts=3)
    rng = np.random.default_rng(0)
    proxies = []
    real_addr = {}
    try:
        fleet.start()
        for rid, h in fleet.replicas.items():
            real_addr[rid] = h.rpc_addr
            p = ChaosProxy(h.rpc_addr, seed=100 + rid, drop_p=0.08,
                           delay_p=0.10, delay_s=0.05)
            proxies.append(p)
            h.rpc_addr = p.addr

        rids = []
        # sustained load with generous deadlines + two hopeless ones
        for i in range(10):
            p = rng.integers(0, cfg.vocab_size, (12,)).astype(np.int32)
            rids.append(fleet.submit(p, max_new_tokens=6,
                                     deadline_s=120.0))
        for _ in range(2):
            p = rng.integers(0, cfg.vocab_size, (12,)).astype(np.int32)
            rids.append(fleet.submit(p, max_new_tokens=40,
                                     deadline_s=0.01))
        deadline = time.monotonic() + 240
        while not all(r in fleet.results for r in rids):
            assert time.monotonic() < deadline, (
                f"hang: {sum(r in fleet.results for r in rids)}"
                f"/{len(rids)} terminal, outstanding={fleet.outstanding}")
            fleet.tick()
            time.sleep(0.01)

        states = {r: fleet.results[r]["state"] for r in rids}
        assert set(states.values()) <= TERMINAL
        assert sum(s == "finished" for s in states.values()) >= 8
        assert any(s == "deadline_exceeded" for s in states.values())

        # one corrupted migration chunk: scripted bitflip on the first
        # KV chunk — the checksum refuses it, the source aborts and
        # stays authoritative, the request still finishes
        src, dest = sorted(fleet.replicas)
        mig_refused = False
        long_rids = []
        for attempt in range(12):
            p = rng.integers(0, cfg.vocab_size, (12,)).astype(np.int32)
            gid = fleet.submit(p, max_new_tokens=64, deadline_s=120.0)
            long_rids.append(gid)
            for _ in range(50):
                fleet.tick()
                rec = fleet._inflight.get(gid)
                if rec is not None and rec.get("replica") is not None:
                    break
                if gid in fleet.results:
                    break
                time.sleep(0.01)
            rec = fleet._inflight.get(gid)
            if rec is None or rec.get("replica") is None:
                continue
            s, d = rec["replica"], None
            d = next(r for r in fleet.replicas if r != s)
            with ChaosProxy(real_addr[d],
                            schedule=["ok", "bitflip"]) as mig_proxy:
                reply = _rpc_request(
                    real_addr[s],
                    {"op": "migrate_out", "rid": gid,
                     "dest": list(mig_proxy.addr)},
                    timeout=30.0, retries=0)
            if reply.get("migrated") is False \
                    and reply.get("reason") not in (None, "not_running",
                                                    "engine_unsupported"):
                mig_refused = True
                break
        assert mig_refused, "corrupted-chunk refusal never exercised"
        deadline = time.monotonic() + 240
        while not all(r in fleet.results for r in long_rids):
            assert time.monotonic() < deadline
            fleet.tick()
            time.sleep(0.01)
        assert {fleet.results[r]["state"]
                for r in long_rids} <= TERMINAL

        # chaos actually happened + the breaker both opened and closed
        total_faults = {}
        for p in proxies:
            for k, v in p.fault_counts().items():
                total_faults[k] = total_faults.get(k, 0) + v
        assert total_faults.get("drop", 0) + total_faults.get(
            "delay", 0) > 0
        # the supervision poll is the half-open probe: keep ticking
        # until the opened breaker has also closed
        deadline = time.monotonic() + 60
        while {"open", "close"} - {e["event"]
                                   for e in fleet.breaker_events}:
            assert time.monotonic() < deadline, (
                f"breaker transitions missing: {fleet.breaker_events}")
            fleet.tick()
            time.sleep(0.02)
        st = fleet.fleet_status()
        assert st["overload"]["breakers"]
        assert st["overload"]["deadline_exceeded"] >= 1

        # zero leaked KV pages: with the prefix cache off, a fully
        # terminal fleet must return every page to its pools
        deadline = time.monotonic() + 60
        while True:
            fleet.tick()
            pools = [(h.last_status or {}).get("kv_pool") or {}
                     for h in fleet.replicas.values()]
            if pools and all(p.get("pages_in_use") == 0 for p in pools):
                break
            assert time.monotonic() < deadline, f"leaked pages: {pools}"
            time.sleep(0.05)
        assert fleet.outstanding == 0

        # lock witness: the run exercised real lock nesting, and the
        # witnessed graph has no lock-order cycle
        snap = lockwitness.snapshot()
        assert snap["waits"], "witness observed no lock activity"
        assert lockwitness.cycles() == [], (
            f"witnessed lock-order cycle: {lockwitness.cycles()} "
            f"(edges: {[(e['src'], e['dst']) for e in snap['edges']]})")
    finally:
        for rid, h in fleet.replicas.items():
            if rid in real_addr:
                h.rpc_addr = real_addr[rid]
        fleet.shutdown(federate=False)
        for p in proxies:
            p.close()
        lockwitness.reset()


@pytest.mark.slow
def test_chaos_loop_with_process_faults(tmp_path, monkeypatch):
    """Slow chaos loop: proxy faults + SIGSTOP straggler + SIGKILL,
    deadlines on every request — every request terminal, zero hangs."""
    from paddle_tpu.distributed.fleet.elastic.fault_injection import (
        kill_replica, pause_replica, resume_replica)
    from paddle_tpu.serving.fleet import FleetRouter
    _drain_env(monkeypatch)
    monkeypatch.setenv("PADDLE_FLEET_BREAKER_FAILS", "2")
    cfg = _fleet_cfg()
    fleet = FleetRouter(cfg, n_replicas=2,
                        engine_kwargs=dict(CHAOS_ENGINE_KW),
                        run_dir=str(tmp_path / "run"), seed=1,
                        max_restarts=6)
    rng = np.random.default_rng(1)
    proxies, real_addr = [], {}

    def interpose(rid, h):
        real_addr[rid] = h.rpc_addr
        p = ChaosProxy(h.rpc_addr, seed=200 + rid, drop_p=0.06,
                       delay_p=0.08, delay_s=0.04)
        proxies.append(p)
        h.rpc_addr = p.addr
    try:
        fleet.start()
        for rid, h in fleet.replicas.items():
            interpose(rid, h)
        rids, n_total = [], 30
        paused = killed = False
        pause_at, kill_at = 8, 16
        paused_rid = None
        deadline = time.monotonic() + 420
        while not (len(rids) == n_total
                   and all(r in fleet.results for r in rids)):
            assert time.monotonic() < deadline, (
                f"hang: {sum(r in fleet.results for r in rids)}"
                f"/{len(rids)}, outstanding={fleet.outstanding}")
            if len(rids) < n_total:
                p = rng.integers(0, cfg.vocab_size, (12,)).astype(
                    np.int32)
                rids.append(fleet.submit(p, max_new_tokens=6,
                                         deadline_s=90.0))
            fleet.tick()
            done = sum(r in fleet.results for r in rids)
            if not paused and done >= pause_at and fleet.replicas:
                paused_rid = sorted(fleet.replicas)[0]
                pause_replica(fleet, paused_rid)
                paused = True
            if paused and paused_rid in fleet.replicas \
                    and done >= pause_at + 4:
                try:
                    resume_replica(fleet, paused_rid)
                except Exception:
                    pass                    # already shed / relaunched
                paused_rid = None
            if not killed and done >= kill_at and fleet._inflight:
                target = next(
                    (rec["replica"] for rec in fleet._inflight.values()
                     if rec.get("replica") is not None), None)
                if target is not None:
                    kill_replica(fleet, target)
                    killed = True
            # a relaunched replica gets its own proxy
            for rid, h in fleet.replicas.items():
                if rid not in real_addr and h.rpc_addr is not None:
                    interpose(rid, h)
            time.sleep(0.01)
        assert killed
        states = {fleet.results[r]["state"] for r in rids}
        assert states <= TERMINAL
        assert sum(fleet.results[r]["state"] == "finished"
                   for r in rids) >= n_total // 2
    finally:
        for rid, h in fleet.replicas.items():
            if rid in real_addr:
                h.rpc_addr = real_addr[rid]
        fleet.shutdown(federate=False)
        for p in proxies:
            p.close()


if __name__ == "__main__":
    raise SystemExit(pytest.main([__file__, "-v"]))
