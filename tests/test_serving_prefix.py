"""Prefix-cache sharing, chunked prefill, and disaggregated serving.

The PR-11 tentpole: the KV page pool as a shared radix cache
(refcounted pages, COW boundary pages, LRU eviction), chunked prefill
that bounds per-tick decode stall, and the disaggregated prefill/decode
split. The load-bearing assertions are token-for-token equivalence —
every engine mode must reproduce the plain PR-8 engine's greedy outputs
exactly — and refcount conservation (no leaked or double-freed pages).
"""
import numpy as np
import pytest
import jax

import paddle_tpu as paddle
from paddle_tpu.serving import (ContinuousBatchingScheduler, PagePool,
                                PagePoolError, ServingEngine,
                                simulate_decode_signatures)
from paddle_tpu.serving.prefix_cache import (PrefixCache,
                                             make_shared_prefix_workload)


def _tiny_model(seed=0):
    from paddle_tpu.models.gpt import (GPTForPretraining, GPTModel,
                                       gpt_tiny_config)
    paddle.seed(seed)
    cfg = gpt_tiny_config()
    return GPTForPretraining(GPTModel(cfg)), cfg


def _prompts(cfg, lens, seed=0):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, cfg.vocab_size, (s,)).astype(np.int32)
            for s in lens]


def _run(engine, prompts, max_new, budget=None):
    sched = ContinuousBatchingScheduler(engine,
                                        prefill_token_budget=budget)
    reqs = [sched.submit(p, max_new_tokens=max_new) for p in prompts]
    sched.run()
    assert all(r.state == "finished" for r in reqs), \
        [r.state for r in reqs]
    return sched, reqs


# ------------------------------------------------------------- pool API

def test_pool_errors_name_the_sequence_and_refcounts():
    pool = PagePool(num_pages=9, page_size=4, num_layers=1,
                    num_kv_heads=1, head_dim=4)
    with pytest.raises(PagePoolError, match="'ghost'"):
        pool.free("ghost")
    with pytest.raises(PagePoolError, match="'ghost'"):
        pool.extend("ghost")
    with pytest.raises(PagePoolError, match="'ghost'"):
        pool.seq_len("ghost")
    with pytest.raises(PagePoolError, match="'ghost'"):
        pool.table("ghost")
    pool.alloc("a", 6)
    pool.free("a")
    with pytest.raises(PagePoolError, match="already-freed"):
        pool.free("a")                          # double free, not KeyError
    # refcount sharing: two sequences mapping one page
    pages = pool.alloc("x", 8)                  # 2 full pages
    pool.alloc_prefixed("y", 10, pages, 8)      # shares both + 1 fresh
    assert pool.page_ref(pages[0]) == 2
    assert pool.stats()["pages_shared"] == 2
    pool.free("x")
    assert pool.page_ref(pages[0]) == 1         # still held by y
    pool.free("y")
    assert pool.page_ref(pages[0]) == 0
    assert pool.free_pages == 8


def test_pool_cow_write_barrier():
    """extend() refuses to grow a sequence into a shared page — the
    write path is COW-aware at the pool level, whatever drives it."""
    pool = PagePool(num_pages=9, page_size=4, num_layers=1,
                    num_kv_heads=1, head_dim=4)
    pages = pool.alloc("a", 8)
    # b maps a's pages with a PARTIAL boundary page (the engine would
    # COW this; the pool-level barrier is the backstop)
    pool.alloc_prefixed("b", 7, pages, 7)
    with pytest.raises(PagePoolError, match="shared page"):
        pool.extend("b", 1)                     # would write page 1 @ref 2
    pool.free("a")                              # ref drops to 1 (b only)
    assert pool.page_ref(pages[1]) == 1
    assert pool.extend("b", 1) == 8             # now exclusive: writable


def test_pool_stats_new_fields_default_zero():
    pool = PagePool(num_pages=5, page_size=4, num_layers=1,
                    num_kv_heads=1, head_dim=4)
    st = pool.stats()
    assert st["pages_shared"] == 0
    assert st["tokens_reused"] == 0
    assert st["prefix_hit_rate"] == 0.0


# ------------------------------------------------------- trie unit tests

def test_prefix_cache_trie_match_insert_evict():
    pool = PagePool(num_pages=17, page_size=4, num_layers=1,
                    num_kv_heads=1, head_dim=4)
    cache = PrefixCache(pool)
    toks = np.arange(12, dtype=np.int32)        # 3 full pages
    pages = pool.alloc("s", 12)
    assert cache.insert(toks, pages) == 3
    assert pool.page_ref(pages[0]) == 2         # seq + trie
    nodes, boundary, cached = cache.match(np.arange(12, dtype=np.int32))
    assert cached == 11                          # capped at len-1
    assert len(nodes) == 2 and boundary is not None
    assert boundary[1] == 3                      # partial page 3 rows
    # divergent prompt: full match on page 0, partial on page 1
    div = np.arange(12, dtype=np.int32)
    div[6] = 99
    nodes, boundary, cached = cache.match(div)
    assert len(nodes) == 1 and cached == 6 and boundary[1] == 2
    # miss
    nodes, boundary, cached = cache.match(
        np.full(8, 77, np.int32))
    assert not nodes and boundary is None and cached == 0
    # eviction: free the seq, then reclaim — LRU leaves go first and
    # pages actually return to the free list
    pool.free("s")
    free0 = pool.free_pages
    assert cache.reclaim(2) == 2
    assert pool.free_pages == free0 + 2
    assert cache.stats()["nodes"] == 1
    cache.clear()
    assert cache.stats()["nodes"] == 0
    assert pool.free_pages == free0 + 3


def test_prefix_cache_pinned_nodes_survive_reclaim():
    pool = PagePool(num_pages=9, page_size=4, num_layers=1,
                    num_kv_heads=1, head_dim=4)
    cache = PrefixCache(pool)
    toks = np.arange(8, dtype=np.int32)
    pages = pool.alloc("s", 8)
    cache.insert(toks, pages)
    pool.free("s")
    nodes, boundary, cached = cache.match(
        np.concatenate([toks, [1, 2]]).astype(np.int32))
    cache.map_into("t", nodes, boundary)
    assert cache.reclaim(10) == 0               # everything pinned
    cache.release("t")
    assert cache.reclaim(10) == 2               # now evictable


# --------------------------------------------------------- equivalence

def test_shared_prefix_scheduler_equivalence_on_off():
    """The satellite acceptance: greedy outputs with prefix cache ON ==
    OFF, token for token, over a shared-prefix workload including a
    mid-page (COW-boundary) divergence — and the pool proves reuse."""
    model, cfg = _tiny_model()
    prompts = make_shared_prefix_workload(
        cfg.vocab_size, 6, prefix_len=24, suffix_len=6, seed=3,
        divergence_offsets=(0, 0, 0, 5, 0, 0))  # req 3 diverges mid-page
    eng_off = ServingEngine(model, page_size=8,
                            decode_buckets=(1, 2, 4, 8), aot=False)
    eng_on = ServingEngine(model, page_size=8,
                           decode_buckets=(1, 2, 4, 8), aot=False,
                           prefix_cache=True, prefill_chunk=16)
    _, r_off = _run(eng_off, prompts, max_new=5)
    s_on, r_on = _run(eng_on, prompts, max_new=5)
    for a, b in zip(r_off, r_on):
        np.testing.assert_array_equal(a.output_ids, b.output_ids)
    cached = [r.cached_prefix_len for r in r_on]
    assert cached[0] == 0                        # first = cold miss
    assert cached[1] == 24 and cached[2] == 24   # full-prefix hits
    assert cached[3] == 19                       # COW: 16 full + 3 partial
    st = eng_on.pool.stats()
    assert st["tokens_reused"] == sum(cached)
    assert st["prefix_hit_rate"] > 0.5
    # refcount conservation after drain: only the trie holds pages
    assert eng_on.pool.live_sequences == 0
    assert all(c == 1 for c in eng_on.pool._refs.values())
    # summaries carry the reuse fields
    s = r_on[3].summary()
    assert s["cached_prefix_len"] == 19 and s["prefill_chunks"] >= 1


def test_prefix_sharing_happens_in_flight():
    """Same-prefix requests admitted in one wave share pages while
    running (pages_shared > 0 mid-flight), not just sequentially."""
    model, cfg = _tiny_model(seed=1)
    prompts = make_shared_prefix_workload(
        cfg.vocab_size, 5, prefix_len=24, suffix_len=6, seed=4)
    eng = ServingEngine(model, page_size=8, decode_buckets=(1, 2, 4, 8),
                        aot=False, prefix_cache=True, prefill_chunk=16)
    sched = ContinuousBatchingScheduler(eng)
    for p in prompts:
        sched.submit(p, max_new_tokens=4)
    max_shared = 0
    while sched.pending:
        sched.step()
        max_shared = max(max_shared, eng.pool.stats()["pages_shared"])
    assert max_shared > 0


def test_prefix_cache_eviction_under_page_pressure():
    """A pool too small for cache + new work reclaims cached pages
    (LRU) instead of refusing admission — and outputs stay correct."""
    model, cfg = _tiny_model(seed=2)
    kw = dict(page_size=8, num_pages=9, max_seq_len=48,
              decode_buckets=(1,), aot=False)
    eng = ServingEngine(model, prefix_cache=True, prefill_chunk=8, **kw)
    sched = ContinuousBatchingScheduler(eng)
    pa, pb = _prompts(cfg, (24, 40), seed=7)
    ra = sched.submit(pa, max_new_tokens=4)
    sched.run()
    assert eng.prefix_cache.stats()["nodes"] > 0
    rb = sched.submit(pb, max_new_tokens=6)     # needs reclaimed pages
    sched.run()
    assert rb.state == "finished"
    assert eng.prefix_cache.evictions > 0
    plain = ServingEngine(model, **kw)
    ps = ContinuousBatchingScheduler(plain)
    xa = ps.submit(pa, max_new_tokens=4); ps.run()
    xb = ps.submit(pb, max_new_tokens=6); ps.run()
    np.testing.assert_array_equal(ra.output_ids, xa.output_ids)
    np.testing.assert_array_equal(rb.output_ids, xb.output_ids)


def test_multi_turn_release_insert_enables_followup_hits():
    """Insert-on-release covers generated tokens: a follow-up turn
    whose prompt extends (prompt + completion) hits the cache."""
    model, cfg = _tiny_model(seed=3)
    eng = ServingEngine(model, page_size=8, decode_buckets=(1, 2),
                        aot=False, prefix_cache=True, prefill_chunk=8)
    sched = ContinuousBatchingScheduler(eng)
    (p1,) = _prompts(cfg, (16,), seed=8)
    r1 = sched.submit(p1, max_new_tokens=9)
    sched.run()
    # next turn: history = prompt + ALL generated tokens + new user turn
    follow = np.concatenate(
        [p1, np.asarray(r1.tokens, np.int32),
         _prompts(cfg, (4,), seed=9)[0]])
    r2 = sched.submit(follow, max_new_tokens=3)
    sched.run()
    # KV exists for prompt+tokens[:-1] = 24 tokens = 3 full pages
    assert r2.cached_prefix_len >= 24
    plain = ServingEngine(model, page_size=8, decode_buckets=(1, 2),
                          aot=False)
    ps = ContinuousBatchingScheduler(plain)
    y = ps.submit(follow, max_new_tokens=3)
    ps.run()
    np.testing.assert_array_equal(r2.output_ids, y.output_ids)


# ------------------------------------------------------ chunked prefill

def test_chunked_prefill_equivalence_and_stall_bound():
    """Chunked engine == unchunked engine token for token; per-tick
    prefill work never exceeds the budget; and decode PROGRESSES while
    a long prompt is prefilling (the stall bound, deterministically)."""
    model, cfg = _tiny_model(seed=4)
    rng = np.random.default_rng(11)
    short = rng.integers(0, cfg.vocab_size, (5,)).astype(np.int32)
    llong = rng.integers(0, cfg.vocab_size, (60,)).astype(np.int32)

    def drive(engine):
        sched = ContinuousBatchingScheduler(engine)
        r_s = sched.submit(short, max_new_tokens=20)
        sched.step(); sched.step()
        toks0 = len(r_s.tokens)
        r_l = sched.submit(llong, max_new_tokens=2)
        during = []
        while sched.pending:
            sched.step()
            if r_l.state == "prefilling":
                during.append(len(r_s.tokens))
        return sched, r_s, r_l, toks0, during

    chunked = ServingEngine(model, page_size=8, decode_buckets=(1, 2),
                            aot=False, prefill_chunk=8)
    s_c, rc_s, rc_l, toks0, during = drive(chunked)
    assert max(s_c.prefill_tokens_per_tick) <= 8  # budget bound
    # the long prompt spanned multiple ticks AND decode moved meanwhile
    assert during and during[-1] > toks0
    plain = ServingEngine(model, page_size=8, decode_buckets=(1, 2),
                          aot=False)
    _, rp_s, rp_l, _, _ = drive(plain)
    np.testing.assert_array_equal(rc_s.output_ids, rp_s.output_ids)
    np.testing.assert_array_equal(rc_l.output_ids, rp_l.output_ids)


def test_chunked_engine_validation_and_direct_prefill():
    model, _ = _tiny_model(seed=5)
    with pytest.raises(ValueError, match="multiple of"):
        ServingEngine(model, page_size=8, prefill_chunk=12, aot=False)
    eng = ServingEngine(model, page_size=8, decode_buckets=(1,),
                        aot=False, prefill_chunk=16)
    # engine.prefill() drives chunks internally for non-scheduler users
    tok = eng.prefill("a", np.zeros(20, np.int32))
    plain = ServingEngine(model, page_size=8, decode_buckets=(1,),
                          aot=False)
    assert tok == plain.prefill("a", np.zeros(20, np.int32))


def test_chunked_aot_single_program_closure():
    """AOT chunked engine compiles ONE chunk program and serves any
    mix without growing the executable set (never recompiles)."""
    model, cfg = _tiny_model(seed=6)
    eng = ServingEngine(model, page_size=8, decode_buckets=(1, 2),
                        aot=True, prefix_cache=True, prefill_chunk=16)
    assert eng._chunk_exe is not None
    assert not eng._prefill_exe                 # replaced by the chunk
    n_dec = len(eng._decode_exe)
    compile_s0 = eng.compile_s
    _run(eng, _prompts(cfg, (3, 21, 9, 40), seed=12), max_new=3)
    assert len(eng._decode_exe) == n_dec
    assert eng._chunk_exe is not None and eng.compile_s == compile_s0
    assert ("chunk", 16, eng.pool.max_pages_per_seq) \
        in eng.prefill_signatures()


# -------------------------------------------------------- disaggregated

def test_disaggregated_engine_equivalence_and_handoff():
    model, cfg = _tiny_model(seed=7)
    prompts = _prompts(cfg, (7, 13, 30), seed=13)
    plain = ServingEngine(model, page_size=8, decode_buckets=(1, 2),
                          aot=False)
    disagg = ServingEngine(model, page_size=8, decode_buckets=(1, 2),
                           aot=False, disaggregated=True)
    _, r_p = _run(plain, prompts, max_new=4)
    _, r_d = _run(disagg, prompts, max_new=4)
    for a, b in zip(r_p, r_d):
        np.testing.assert_array_equal(a.output_ids, b.output_ids)
    assert disagg.kv_transfers == len(prompts)
    assert disagg.kv_transfer_bytes > 0
    st = disagg.status()["disaggregated"]
    assert st["kv_transfers"] == 3 and st["kv_transfer_mb"] > 0
    sigs = disagg.prefill_signatures()
    assert any(s[0] == "disagg" for s in sigs)
    assert any(s[0] == "scatter" for s in sigs)


def test_disaggregated_aot_cross_device():
    """AOT executables must compile FOR each side's device: under the
    8-device test mesh, prefill lands on device 0 and decode on device
    7 — a default-device compile would reject the committed pool
    arrays at the first decode (the exact multi-topology crash the
    single-device smoke can't see)."""
    if len(jax.devices()) < 2:
        pytest.skip("needs >= 2 devices")
    model, cfg = _tiny_model(seed=10)
    eng = ServingEngine(model, page_size=8, decode_buckets=(1, 2),
                        prefill_buckets=(16, 128), aot=True,
                        disaggregated=True)
    st = eng.status()["disaggregated"]
    assert st["prefill_device"] != st["decode_device"]
    plain = ServingEngine(model, page_size=8, decode_buckets=(1, 2),
                          aot=False)
    prompts = _prompts(cfg, (7, 13), seed=14)
    _, r_d = _run(eng, prompts, max_new=4)
    _, r_p = _run(plain, prompts, max_new=4)
    for a, b in zip(r_d, r_p):
        np.testing.assert_array_equal(a.output_ids, b.output_ids)
    # transfer accounting books the TRUE payload (prompt positions),
    # not the bucket-padded tensor
    L, nkv, d = cfg.num_layers, cfg.num_heads, cfg.head_dim
    assert eng.kv_transfer_bytes == 2 * L * (7 + 13) * nkv * d * 4


def test_disaggregated_rejects_prefix_cache():
    model, _ = _tiny_model(seed=8)
    with pytest.raises(ValueError, match="disaggregated"):
        ServingEngine(model, page_size=8, disaggregated=True,
                      prefix_cache=True, aot=False)


# ------------------------------------------------- closure + metrics

def test_closure_simulation_all_modes():
    """used ⊆ allowed for classic, chunked, and disaggregated modes —
    what the check_program serving gate replays."""
    for kw in (dict(), dict(prefill_chunk=16), dict(disaggregated=True)):
        ud, up, okd, okp = simulate_decode_signatures(
            (1, 2, 4), (8, 16, 32, 64, 128), 8, 129, 128,
            n_requests=80, seed=1, **kw)
        assert ud and ud <= okd, (kw, ud, okd)
        assert up and up <= okp, (kw, up, okp)


def test_prefix_metrics_and_request_records():
    from paddle_tpu.observability import get_registry
    from paddle_tpu.observability.reqtrace import fold_request_records
    model, cfg = _tiny_model(seed=9)
    reg = get_registry()

    def val(name):
        inst = reg.get(name)
        if inst is None:
            return 0.0
        return sum(state.get("value", state.get("count", 0.0))
                   for _, state in inst.collect())

    hits0 = val("paddle_serving_prefix_cache_hits_total")
    reused0 = val("paddle_serving_prefix_tokens_reused_total")
    chunks0 = val("paddle_serving_prefill_chunks_total")
    prompts = make_shared_prefix_workload(
        cfg.vocab_size, 4, prefix_len=16, suffix_len=8, seed=5)
    eng = ServingEngine(model, page_size=8, decode_buckets=(1, 2, 4),
                        aot=False, prefix_cache=True, prefill_chunk=8)
    _, reqs = _run(eng, prompts, max_new=3)
    assert val("paddle_serving_prefix_cache_hits_total") >= hits0 + 3
    assert val("paddle_serving_prefix_tokens_reused_total") \
        >= reused0 + 3 * 16
    assert val("paddle_serving_prefill_chunks_total") > chunks0
    # requests.jsonl folding: skipped prefill work is accounted
    folded = fold_request_records([r.summary() | {"event": "request"}
                                   for r in reqs])
    assert folded["cached_prefix_tokens_total"] == sum(
        r.cached_prefix_len for r in reqs)
    assert folded["prefix_hit_requests"] == 3
    assert folded["prefill_chunks_total"] >= 4
    # /status carries the new pool fields + prefix cache section
    sched = ContinuousBatchingScheduler(eng)
    st = sched.status()
    assert "prefix_hit_rate" in st["kv_pool"]
    assert "tokens_reused" in st["kv_pool"]
    assert "prefilling" in st
    assert "prefix_cache" in st["engine"]


def test_predicted_shared_prefix_and_disagg_rows():
    from paddle_tpu.serving.predict import (predicted_disagg_row,
                                            predicted_shared_prefix_row)
    row = predicted_shared_prefix_row("tiny", concurrency=4,
                                      prompt_len=64,
                                      shared_fraction=0.75, max_new=8,
                                      prefill_chunk=16, page_size=8)
    assert row["predicted_tokens_per_sec"] > 0
    assert row["predicted_tokens_per_sec"] \
        > row["predicted_tokens_per_sec_no_cache"]
    assert row["predicted_ttft_speedup"] > 1
    assert row["predicted_tokens_reused"] == 3 * 48
    d = predicted_disagg_row("tiny", concurrency=4, prompt_len=48,
                             page_size=8)
    assert d["predicted_tokens_per_sec"] > 0
    assert d["predicted_ttft_ms"] >= d["predicted_prefill_ms"]
    assert d["predicted_kv_transfer_mb"] > 0
