"""Sparse + fft/signal tests (numpy/scipy-free oracles: dense numpy + torch).

Parity model: reference unittests/test_sparse_*.py compare against dense
equivalents; fft tests against numpy.fft; stft/istft round-trip.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import sparse, fft, signal


def _np(t):
    return np.asarray(t._value)


def _coo_from_dense(d):
    idx = np.nonzero(d)
    vals = d[idx]
    return sparse.sparse_coo_tensor(np.stack(idx), vals, d.shape)


def test_coo_create_to_dense_roundtrip():
    d = np.array([[0, 1, 0], [2, 0, 3]], np.float32)
    s = _coo_from_dense(d)
    assert s.shape == [2, 3] and s.nnz == 3
    np.testing.assert_allclose(_np(s.to_dense()), d)
    np.testing.assert_allclose(np.asarray(s.indices()._value),
                               np.stack(np.nonzero(d)))
    np.testing.assert_allclose(np.asarray(s.values()._value), [1, 2, 3])


def test_csr_roundtrip():
    d = np.array([[0, 1, 0], [2, 0, 3], [0, 0, 0]], np.float32)
    coo = _coo_from_dense(d)
    csr = coo.to_sparse_csr()
    np.testing.assert_allclose(np.asarray(csr.crows()._value), [0, 1, 3, 3])
    np.testing.assert_allclose(np.asarray(csr.cols()._value), [1, 0, 2])
    np.testing.assert_allclose(_np(csr.to_dense()), d)
    back = csr.to_sparse_coo()
    np.testing.assert_allclose(_np(back.to_dense()), d)


def test_sparse_csr_tensor_creation():
    csr = sparse.sparse_csr_tensor([0, 1, 3], [1, 0, 2],
                                   [1.0, 2.0, 3.0], [2, 3])
    d = np.array([[0, 1, 0], [2, 0, 3]], np.float32)
    np.testing.assert_allclose(_np(csr.to_dense()), d)


def test_sparse_unary_binary():
    d1 = np.array([[0, -1.0], [2.0, 0]], np.float32)
    d2 = np.array([[1.0, 0], [-3.0, 0]], np.float32)
    s1, s2 = _coo_from_dense(d1), _coo_from_dense(d2)
    np.testing.assert_allclose(_np(sparse.relu(s1).to_dense()),
                               np.maximum(d1, 0))
    np.testing.assert_allclose(_np(sparse.add(s1, s2).to_dense()), d1 + d2)
    np.testing.assert_allclose(_np(sparse.subtract(s1, s2).to_dense()),
                               d1 - d2)
    np.testing.assert_allclose(_np(sparse.multiply(s1, s2).to_dense()),
                               d1 * d2)


def test_sparse_matmul():
    rng = np.random.default_rng(0)
    d = rng.standard_normal((4, 6)).astype(np.float32)
    d[d < 0.3] = 0
    dense = rng.standard_normal((6, 5)).astype(np.float32)
    s = _coo_from_dense(d)
    out = sparse.matmul(s, paddle.to_tensor(dense))
    np.testing.assert_allclose(_np(out), d @ dense, rtol=1e-5, atol=1e-5)


def test_masked_matmul():
    rng = np.random.default_rng(1)
    x = rng.standard_normal((4, 8)).astype(np.float32)
    y = rng.standard_normal((8, 4)).astype(np.float32)
    mask_d = (rng.random((4, 4)) > 0.5).astype(np.float32)
    m = _coo_from_dense(mask_d)
    out = sparse.masked_matmul(paddle.to_tensor(x), paddle.to_tensor(y), m)
    np.testing.assert_allclose(_np(out.to_dense()), (x @ y) * mask_d,
                               rtol=1e-4, atol=1e-4)


def test_sparse_nn_softmax():
    d = np.array([[0, 1.0, 2.0], [3.0, 0, 0]], np.float32)
    csr = _coo_from_dense(d).to_sparse_csr()
    out = sparse.nn.Softmax()(csr).to_dense()
    want = np.zeros_like(d)
    want[0, 1:] = np.exp([1.0, 2.0]) / np.exp([1.0, 2.0]).sum()
    want[1, 0] = 1.0
    np.testing.assert_allclose(_np(out), want, rtol=1e-5)


# ------------------------------------------------------------------- fft
def test_fft_matches_numpy():
    rng = np.random.default_rng(0)
    x = rng.standard_normal(32).astype(np.float32)
    np.testing.assert_allclose(_np(fft.fft(paddle.to_tensor(x))),
                               np.fft.fft(x), rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(_np(fft.rfft(paddle.to_tensor(x))),
                               np.fft.rfft(x), rtol=1e-4, atol=1e-4)
    x2 = rng.standard_normal((8, 8)).astype(np.float32)
    np.testing.assert_allclose(_np(fft.fft2(paddle.to_tensor(x2))),
                               np.fft.fft2(x2), rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(
        _np(fft.ifft(fft.fft(paddle.to_tensor(x)))).real, x,
        rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(_np(fft.fftshift(paddle.to_tensor(x))),
                               np.fft.fftshift(x), rtol=1e-6)
    np.testing.assert_allclose(_np(fft.fftfreq(16, 0.5)),
                               np.fft.fftfreq(16, 0.5), rtol=1e-6)


def test_fft_norm_and_grad():
    x = paddle.to_tensor(np.random.default_rng(1)
                         .standard_normal(16).astype(np.float32))
    x.stop_gradient = False
    from paddle_tpu import ops
    y = fft.rfft(x, norm="ortho")
    loss = ops.sum(ops.abs(y) ** 2)
    loss.backward()
    assert x.grad is not None
    # Parseval under ortho norm... rfft halves, so just check finiteness
    assert np.isfinite(np.asarray(x.grad._value)).all()


def test_stft_istft_roundtrip():
    rng = np.random.default_rng(2)
    sig = rng.standard_normal(512).astype(np.float32)
    n_fft, hop = 64, 16
    window = np.hanning(n_fft).astype(np.float32)
    spec = signal.stft(paddle.to_tensor(sig[None]), n_fft, hop_length=hop,
                       window=paddle.to_tensor(window))
    assert _np(spec).shape[1] == n_fft // 2 + 1
    back = signal.istft(spec, n_fft, hop_length=hop,
                        window=paddle.to_tensor(window), length=512)
    np.testing.assert_allclose(_np(back)[0], sig, rtol=1e-3, atol=1e-3)


def test_stft_matches_torch():
    import torch
    rng = np.random.default_rng(3)
    sig = rng.standard_normal(256).astype(np.float32)
    n_fft, hop = 32, 8
    win = np.hanning(n_fft).astype(np.float32)
    ours = _np(signal.stft(paddle.to_tensor(sig[None]), n_fft,
                           hop_length=hop, window=paddle.to_tensor(win)))[0]
    theirs = torch.stft(torch.tensor(sig), n_fft, hop_length=hop,
                        window=torch.tensor(win), center=True,
                        pad_mode="reflect", return_complex=True).numpy()
    np.testing.assert_allclose(ours, theirs, rtol=1e-3, atol=1e-3)


def test_frame_overlap_add():
    x = np.arange(16, dtype=np.float32)
    f = signal.frame(paddle.to_tensor(x), frame_length=4, hop_length=2)
    assert _np(f).shape == (4, 7)
    np.testing.assert_allclose(_np(f)[:, 0], [0, 1, 2, 3])
    back = signal.overlap_add(f, hop_length=2)
    # each sample appears twice except the edges
    assert _np(back).shape == (16,)
