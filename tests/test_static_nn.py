"""paddle.static.nn layer-building functions: record into a Program and
execute with trained parameters (reference static/nn/common.py surface)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import static
from paddle_tpu.static import nn as snn


def _run(build, feeds):
    static.enable_static()
    try:
        main = static.Program()
        with static.program_guard(main):
            fetch = build()
        exe = static.Executor()
        return exe.run(main, feed=feeds, fetch_list=[fetch])[0]
    finally:
        static.disable_static()


def test_fc_flatten_and_activation():
    x_np = np.random.default_rng(0).standard_normal((2, 3, 4)) \
        .astype(np.float32)

    def build():
        x = static.data("x", [2, 3, 4], "float32")
        return snn.fc(x, size=5, num_flatten_dims=1, activation="relu")

    out = _run(build, {"x": x_np})
    assert out.shape == (2, 5)
    assert (out >= 0).all()


def test_embedding_and_conv2d():
    ids_np = np.array([[1, 2], [3, 0]], np.int64)

    def build():
        ids = static.data("ids", [2, 2], "int64")
        return snn.embedding(ids, size=[10, 6])

    assert _run(build, {"ids": ids_np}).shape == (2, 2, 6)

    img_np = np.random.default_rng(0).standard_normal((2, 3, 8, 8)) \
        .astype(np.float32)

    def build2():
        img = static.data("img", [2, 3, 8, 8], "float32")
        return snn.conv2d(img, num_filters=4, filter_size=3, padding=1,
                          act="relu")

    out = _run(build2, {"img": img_np})
    assert out.shape == (2, 4, 8, 8) and (out >= 0).all()


def test_norms_and_prelu():
    x_np = np.random.default_rng(0).standard_normal((2, 4, 6, 6)) \
        .astype(np.float32)

    def build():
        x = static.data("x", [2, 4, 6, 6], "float32")
        h = snn.batch_norm(x)
        h = snn.group_norm(h, groups=2)
        h = snn.instance_norm(h)
        return snn.prelu(h, mode="channel")

    assert _run(build, {"x": x_np}).shape == (2, 4, 6, 6)

    def build_ln():
        x = static.data("x", [2, 4, 6, 6], "float32")
        return snn.layer_norm(x, begin_norm_axis=2)

    out = _run(build_ln, {"x": x_np})
    np.testing.assert_allclose(out.mean(axis=(2, 3)), 0.0, atol=1e-4)


def test_fc_keeps_leading_dims():
    x_np = np.ones((2, 3, 4, 5), np.float32)

    def build():
        x = static.data("x", [2, 3, 4, 5], "float32")
        return snn.fc(x, size=7, num_flatten_dims=2)

    assert _run(build, {"x": x_np}).shape == (2, 3, 7)


def test_prelu_element_mode():
    x_np = np.random.default_rng(0).standard_normal((2, 3, 4, 4)) \
        .astype(np.float32)

    def build():
        x = static.data("x", [2, 3, 4, 4], "float32")
        return snn.prelu(x, mode="element")

    out = _run(build, {"x": x_np})
    # default alpha 0.25: negatives scaled, positives passed through
    np.testing.assert_allclose(
        out, np.where(x_np > 0, x_np, 0.25 * x_np), rtol=1e-5)


def test_bilinear_and_fc_multi_input():
    a_np = np.ones((3, 4), np.float32)
    b_np = np.ones((3, 5), np.float32)

    def build():
        a = static.data("a", [3, 4], "float32")
        b = static.data("b", [3, 5], "float32")
        return snn.bilinear_tensor_product(a, b, size=7)

    assert _run(build, {"a": a_np, "b": b_np}).shape == (3, 7)

    def build2():
        a = static.data("a", [3, 4], "float32")
        b = static.data("b", [3, 5], "float32")
        return snn.fc([a, b], size=6)

    assert _run(build2, {"a": a_np, "b": b_np}).shape == (3, 6)


def test_conv_transpose_output_size_honored():
    x_np = np.ones((1, 3, 8, 8), np.float32)

    def build():
        x = static.data("x", [1, 3, 8, 8], "float32")
        # k=3, s=2, in=8 -> ambiguity window [17, 18]; request 18
        return snn.conv2d_transpose(x, 4, filter_size=3, stride=2,
                                    output_size=[18, 18])

    assert _run(build, {"x": x_np}).shape == (1, 4, 18, 18)

    # derived kernel from output_size, no filter_size
    def build2():
        x = static.data("x", [1, 3, 8, 8], "float32")
        return snn.conv2d_transpose(x, 4, stride=2, output_size=[17, 17])

    assert _run(build2, {"x": x_np}).shape == (1, 4, 17, 17)

    # unreachable size names the valid window
    def build3():
        x = static.data("x", [1, 3, 8, 8], "float32")
        return snn.conv2d_transpose(x, 4, filter_size=3, stride=2,
                                    output_size=[40, 40])

    with pytest.raises(ValueError, match="unreachable"):
        _run(build3, {"x": x_np})

    # string padding cannot derive a kernel: clear error
    def build4():
        x = static.data("x", [1, 3, 8, 8], "float32")
        return snn.conv2d_transpose(x, 4, stride=2, output_size=[16, 16],
                                    padding="SAME")

    with pytest.raises(ValueError, match="filter_size"):
        _run(build4, {"x": x_np})


def test_py_func_eager_and_lazy():
    doubled = snn.py_func(lambda t: t * 2, paddle.to_tensor(
        np.array([1.0, 2.0], np.float32)), out=None)
    np.testing.assert_allclose(np.asarray(doubled.numpy()), [2.0, 4.0])

    static.enable_static()
    try:
        main = static.Program()
        with static.program_guard(main):
            x = static.data("x", [2], "float32")
            with pytest.raises(NotImplementedError, match="pure_callback"):
                snn.py_func(lambda t: t, x, out=None)
    finally:
        static.disable_static()


def test_static_nn_params_train():
    # fc weights actually update through minimize
    x_np = np.random.default_rng(0).standard_normal((4, 3)).astype(np.float32)
    y_np = np.ones((4, 1), np.float32)
    static.enable_static()
    try:
        from paddle_tpu import optimizer
        main = static.Program()
        with static.program_guard(main):
            x = static.data("x", [4, 3], "float32")
            y = static.data("y", [4, 1], "float32")
            pred = snn.fc(x, size=1)
            loss = paddle.mean(paddle.square(pred - y))
            opt = optimizer.SGD(learning_rate=0.1)
            opt.minimize(loss)
        exe = static.Executor()
        losses = [float(exe.run(main, feed={"x": x_np, "y": y_np},
                                fetch_list=[loss])[0]) for _ in range(5)]
        assert losses[-1] < losses[0]
    finally:
        static.disable_static()


def test_static_bn_updates_running_stats():
    """Static-capture BN threads running mean/var through the Executor's
    buffer channel: stats update per run (reference in-place update of
    batch_norm_kernel.cu), compounding across runs."""
    rng = np.random.default_rng(3)
    x_np = (rng.standard_normal((4, 3, 5, 5)) * 2 + 1).astype(np.float32)

    static.enable_static()
    try:
        main = static.Program()
        with static.program_guard(main):
            x = static.data("x", [4, 3, 5, 5], "float32")
            bn = paddle.nn.BatchNorm2D(3)
            out_v = bn(x)
        assert len(main._buffer_updates) == 2
        exe = static.Executor()
        rm0 = np.array(bn._mean.numpy())
        exe.run(main, feed={"x": x_np}, fetch_list=[out_v])
        rm1 = np.array(bn._mean.numpy())
        rv1 = np.array(bn._variance.numpy())
        assert not np.allclose(rm0, rm1), "running mean did not update"
        batch_mean = x_np.mean(axis=(0, 2, 3))
        batch_var = x_np.var(axis=(0, 2, 3))
        np.testing.assert_allclose(rm1, 0.9 * rm0 + 0.1 * batch_mean,
                                   rtol=1e-5, atol=1e-6)
        # second run compounds on the first (not recomputed from init)
        exe.run(main, feed={"x": x_np}, fetch_list=[out_v])
        rm2 = np.array(bn._mean.numpy())
        np.testing.assert_allclose(rm2, 0.9 * rm1 + 0.1 * batch_mean,
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(
            np.array(bn._variance.numpy()),
            0.9 * rv1 + 0.1 * batch_var, rtol=1e-5, atol=1e-6)
    finally:
        static.disable_static()


def test_static_bn_double_capture_compounds():
    """A BN layer captured TWICE in one program chains its updates so a
    single run compounds both (reference sequential in-place ops)."""
    rng = np.random.default_rng(4)
    x1_np = (rng.standard_normal((4, 3, 5, 5)) + 2).astype(np.float32)
    x2_np = (rng.standard_normal((4, 3, 5, 5)) - 1).astype(np.float32)

    static.enable_static()
    try:
        main = static.Program()
        with static.program_guard(main):
            x1 = static.data("x1", [4, 3, 5, 5], "float32")
            x2 = static.data("x2", [4, 3, 5, 5], "float32")
            bn = paddle.nn.BatchNorm2D(3)
            o = bn(x1) + bn(x2)
        exe = static.Executor()
        rm0 = np.array(bn._mean.numpy())
        exe.run(main, feed={"x1": x1_np, "x2": x2_np}, fetch_list=[o])
        rm1 = np.array(bn._mean.numpy())
        m1 = x1_np.mean(axis=(0, 2, 3))
        m2 = x2_np.mean(axis=(0, 2, 3))
        want = 0.9 * (0.9 * rm0 + 0.1 * m1) + 0.1 * m2
        np.testing.assert_allclose(rm1, want, rtol=1e-5, atol=1e-6)
    finally:
        static.disable_static()
