"""Native TCPStore / elastic manager / auto-checkpoint / converter tests.

Parity model: reference store tests (test_tcp_store.py), elastic manager
tests with mocked etcd (test_fleet_elastic_manager.py), auto_checkpoint
tests, and auto_parallel converter tests (slices round-trip).
"""
import os
import threading
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer as opt
from paddle_tpu.distributed.store import TCPStore
from paddle_tpu.distributed.fleet.elastic import (
    ElasticManager, ElasticStatus,
)
from paddle_tpu.distributed.auto_parallel.converter import Converter
from paddle_tpu.incubate.checkpoint.auto_checkpoint import train_epoch_range


# ------------------------------------------------------------- TCPStore
@pytest.fixture(scope="module")
def store_pair():
    master = TCPStore(is_master=True, world_size=2, timeout=10)
    client = TCPStore(port=master.port, world_size=2, timeout=10)
    yield master, client
    client.close()
    master.close()


def test_store_set_get_add(store_pair):
    master, client = store_pair
    assert client.ping()
    master.set("alpha", b"1")
    assert client.get("alpha") == b"1"
    assert client.add("cnt", 3) == 3
    assert master.add("cnt", 4) == 7
    assert client.get_nowait("nope") is None
    master.set("p/x", b"a")
    master.set("p/y", b"b")
    assert sorted(client.keys_with_prefix("p/")) == ["p/x", "p/y"]


def test_store_blocking_get(store_pair):
    master, client = store_pair
    got = []
    t = threading.Thread(target=lambda: got.append(client.get("later")))
    t.start()
    time.sleep(0.2)
    assert not got
    master.set("later", b"now")
    t.join(timeout=5)
    assert got == [b"now"]


def test_store_barrier(store_pair):
    master, client = store_pair
    done = []

    def arrive(s):
        s.barrier("btest")
        done.append(1)

    t1 = threading.Thread(target=arrive, args=(master,))
    t1.start()
    time.sleep(0.15)
    assert not done  # first arrival blocks
    t2 = threading.Thread(target=arrive, args=(client,))
    t2.start()
    t1.join(5)
    t2.join(5)
    assert len(done) == 2


def test_store_get_timeout():
    m = TCPStore(is_master=True, world_size=1, timeout=0.3)
    with pytest.raises(TimeoutError):
        m.get("never_set")
    m.close()


# -------------------------------------------------------------- elastic
def test_elastic_membership_and_levels():
    em = ElasticManager(job_id="j1", np="2:4", host="node1",
                        fault_tolerance_level=1, elastic_ttl=1)
    em.register()
    em2 = ElasticManager(job_id="j1", np="2:4", host="node2",
                         store=em.store, fault_tolerance_level=1,
                         elastic_ttl=1)
    em2.register()
    assert em.wait_ready(timeout=3)
    assert em.hosts() == ["node1", "node2"]
    # decisions
    assert em.pod_leave_status(3) == ElasticStatus.RESTART
    assert em.pod_leave_status(1) == ElasticStatus.HOLD  # level 1 holds
    em0 = ElasticManager(job_id="x", np="2:4", fault_tolerance_level=0)
    assert em0.pod_leave_status(1) == ElasticStatus.ERROR
    # lease expiry drops a node
    em2.stopped = True  # stop node2's keepalive
    time.sleep(1.3)
    assert em.hosts() == ["node1"]
    em.exit()


def test_elastic_watch_fires():
    em = ElasticManager(job_id="j2", np="1:3", host="a", elastic_ttl=5)
    em.register()
    events = []
    em.watch(lambda old, new: events.append((old, new)), interval=0.1)
    em2 = ElasticManager(job_id="j2", np="1:3", host="b", store=em.store,
                         elastic_ttl=5)
    em2.register()
    deadline = time.time() + 3
    while not events and time.time() < deadline:
        time.sleep(0.05)
    assert events and events[0][1] == ["a", "b"]
    assert em.need_sync
    em.exit()
    em2.exit()


def test_elastic_np_parsing():
    assert ElasticManager._parse_np("2:8") == (2, 8)
    assert ElasticManager._parse_np("4") == (4, 4)


def test_elastic_with_tcp_store():
    master = TCPStore(is_master=True, world_size=1, timeout=5)
    em = ElasticManager(job_id="j3", np="1", host="h1", store=master,
                        elastic_ttl=2)
    em.register()
    assert em.hosts() == ["h1"]
    em.exit()
    master.close()


# ------------------------------------------------------- auto-checkpoint
def test_auto_checkpoint_resumes(tmp_path):
    paddle.seed(0)
    ckpt = str(tmp_path)

    def run(crash_at=None):
        net = nn.Linear(4, 4)
        o = opt.SGD(learning_rate=0.1, parameters=net.parameters())
        seen = []
        for epoch in train_epoch_range(5, run_id="t1", checkpoint_dir=ckpt,
                                       model=net, opt=o):
            seen.append(epoch)
            net.weight.set_value(np.full((4, 4), float(epoch), np.float32))
            if crash_at is not None and epoch == crash_at:
                break  # simulated crash AFTER some epochs checkpointed
        return seen, net

    seen1, _ = run(crash_at=2)
    assert seen1 == [0, 1, 2]
    seen2, net2 = run()
    # epochs 0-1 checkpointed (epoch 2 crashed before its save) → resume at 2
    assert seen2 == [2, 3, 4]
    # restored weight is the last checkpointed epoch's value
    first_restored = 1.0
    # run() overwrote weights each epoch, so just assert full completion
    seen3, _ = run()
    assert seen3 == []  # finished; nothing left to do


# ------------------------------------------------------------ converter
def test_converter_reshards():
    full = np.arange(64, dtype=np.float32).reshape(8, 8)
    pre = {"process_shape": [4], "process_group": [0, 1, 2, 3],
           "dims_mapping": [0, -1]}  # row-sharded over 4
    cur = {"process_shape": [2], "process_group": [0, 1],
           "dims_mapping": [-1, 0]}  # col-sharded over 2
    slices = Converter.slice_with_dist_attr(full, pre)
    assert len(slices) == 4 and slices[0].shape == (2, 8)
    merged = Converter.merge_with_dist_attr(slices, pre)
    np.testing.assert_allclose(merged, full)

    conv = Converter({"w": slices}, {"w": pre}, {"w": cur})
    out = conv.convert()
    assert len(out["w"]) == 2 and out["w"][0].shape == (8, 4)
    np.testing.assert_allclose(out["w"][0], full[:, :4])
    np.testing.assert_allclose(out["w"][1], full[:, 4:])


def test_converter_2d_mesh():
    full = np.arange(32, dtype=np.float32).reshape(4, 8)
    attr = {"process_shape": [2, 2], "process_group": [0, 1, 2, 3],
            "dims_mapping": [0, 1]}  # both dims sharded over the 2x2 mesh
    slices = Converter.slice_with_dist_attr(full, attr)
    assert slices[0].shape == (2, 4)
    np.testing.assert_allclose(
        Converter.merge_with_dist_attr(slices, attr), full)
