"""Core Tensor + autograd tape tests (parity model: reference eager autograd tests)."""
import numpy as np
import pytest

import paddle_tpu as paddle


def test_to_tensor_basic():
    t = paddle.to_tensor([[1.0, 2.0], [3.0, 4.0]])
    assert t.shape == [2, 2]
    assert t.dtype == paddle.float32
    np.testing.assert_allclose(t.numpy(), [[1, 2], [3, 4]])


def test_dtypes():
    assert paddle.to_tensor([1, 2]).dtype == paddle.int64
    assert paddle.to_tensor(np.zeros((2,), np.float64)).dtype == paddle.float64
    assert paddle.to_tensor([1.0]).dtype == paddle.float32
    t = paddle.to_tensor([1.0], dtype="bfloat16")
    assert t.dtype == paddle.bfloat16


def test_arithmetic():
    x = paddle.to_tensor([1.0, 2.0, 3.0])
    y = paddle.to_tensor([4.0, 5.0, 6.0])
    np.testing.assert_allclose((x + y).numpy(), [5, 7, 9])
    np.testing.assert_allclose((x * y).numpy(), [4, 10, 18])
    np.testing.assert_allclose((y - x).numpy(), [3, 3, 3])
    np.testing.assert_allclose((y / x).numpy(), [4, 2.5, 2])
    np.testing.assert_allclose((2 * x + 1).numpy(), [3, 5, 7])
    np.testing.assert_allclose((x ** 2).numpy(), [1, 4, 9])
    np.testing.assert_allclose((1 - x).numpy(), [0, -1, -2])


def test_matmul_grad():
    x = paddle.to_tensor(np.random.rand(3, 4).astype("float32"), stop_gradient=False)
    w = paddle.to_tensor(np.random.rand(4, 5).astype("float32"), stop_gradient=False)
    out = paddle.matmul(x, w)
    loss = out.sum()
    loss.backward()
    np.testing.assert_allclose(
        x.grad.numpy(), np.ones((3, 5)) @ w.numpy().T, rtol=1e-5)
    np.testing.assert_allclose(
        w.grad.numpy(), x.numpy().T @ np.ones((3, 5)), rtol=1e-5)


def test_chain_grad():
    x = paddle.to_tensor([2.0], stop_gradient=False)
    y = x * x * x  # y = x^3, dy/dx = 3x^2 = 12
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), [12.0], rtol=1e-6)


def test_grad_accumulation():
    x = paddle.to_tensor([3.0], stop_gradient=False)
    (x * 2).backward()
    (x * 3).backward()
    np.testing.assert_allclose(x.grad.numpy(), [5.0])
    x.clear_grad()
    assert x.grad is None


def test_branching_graph():
    x = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
    a = x * 2
    b = x * 3
    (a + b).sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [5.0, 5.0])


def test_no_grad():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    with paddle.no_grad():
        y = x * 2
    assert y.stop_gradient
    assert y._node is None


def test_stop_gradient_blocks():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    y = (x * 2).detach()
    z = y * 3
    assert z.stop_gradient


def test_double_backward_error_without_retain():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    y = (x * x).sum()
    y.backward()
    with pytest.raises(RuntimeError):
        y.backward()


def test_retain_graph():
    x = paddle.to_tensor([3.0], stop_gradient=False)
    y = (x * x).sum()
    y.backward(retain_graph=True)
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), [12.0])


def test_paddle_grad_api():
    x = paddle.to_tensor([2.0], stop_gradient=False)
    y = x * x
    (gx,) = paddle.framework.grad(y, [x])
    np.testing.assert_allclose(gx.numpy(), [4.0])
    assert x.grad is None  # .grad untouched


def test_multi_output_op_grad():
    x = paddle.to_tensor(np.arange(6, dtype="float32").reshape(2, 3),
                         stop_gradient=False)
    a, b, c = paddle.split(x, 3, axis=1)
    (a.sum() * 2 + b.sum()).backward()
    np.testing.assert_allclose(x.grad.numpy(), [[2, 1, 0], [2, 1, 0]])


def test_indexing_grad():
    x = paddle.to_tensor([1.0, 2.0, 3.0], stop_gradient=False)
    y = x[1:]
    y.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [0, 1, 1])


def test_setitem():
    x = paddle.to_tensor([1.0, 2.0, 3.0])
    x[0] = 9.0
    np.testing.assert_allclose(x.numpy(), [9, 2, 3])


def test_inplace_ops():
    x = paddle.to_tensor([1.0, 2.0])
    x.add_(paddle.to_tensor([1.0, 1.0]))
    np.testing.assert_allclose(x.numpy(), [2, 3])
    x.scale_(2.0)
    np.testing.assert_allclose(x.numpy(), [4, 6])


def test_comparison_and_logic():
    x = paddle.to_tensor([1.0, 2.0, 3.0])
    y = paddle.to_tensor([3.0, 2.0, 1.0])
    np.testing.assert_array_equal((x < y).numpy(), [True, False, False])
    np.testing.assert_array_equal((x == y).numpy(), [False, True, False])
    assert bool(paddle.allclose(x, x))


def test_cast():
    x = paddle.to_tensor([1.5, 2.5])
    assert x.astype("int32").dtype == paddle.int32
    assert x.astype(paddle.float16).dtype == paddle.float16


def test_reductions_match_numpy():
    a = np.random.rand(3, 4, 5).astype("float32")
    x = paddle.to_tensor(a)
    np.testing.assert_allclose(x.sum(axis=1).numpy(), a.sum(1), rtol=1e-5)
    np.testing.assert_allclose(x.mean().numpy(), a.mean(), rtol=1e-5)
    np.testing.assert_allclose(x.max(axis=[0, 2]).numpy(), a.max((0, 2)), rtol=1e-6)
    np.testing.assert_allclose(
        paddle.std(x, axis=0).numpy(), a.std(0, ddof=1), rtol=1e-4)


def test_manipulation_roundtrip():
    a = np.arange(24, dtype="float32").reshape(2, 3, 4)
    x = paddle.to_tensor(a)
    y = paddle.transpose(x, [2, 0, 1])
    assert y.shape == [4, 2, 3]
    z = paddle.reshape(y, [4, -1])
    assert z.shape == [4, 6]
    np.testing.assert_allclose(
        paddle.concat([x, x], axis=0).numpy(), np.concatenate([a, a], 0))
    np.testing.assert_allclose(
        paddle.stack([x, x]).numpy(), np.stack([a, a]))


def test_creation_ops():
    assert paddle.zeros([2, 3]).shape == [2, 3]
    assert paddle.ones([2], dtype="int64").dtype == paddle.int64
    np.testing.assert_allclose(paddle.arange(5).numpy(), np.arange(5))
    np.testing.assert_allclose(paddle.full([2], 7.0).numpy(), [7, 7])
    assert paddle.eye(3).shape == [3, 3]
    paddle.seed(42)
    r1 = paddle.rand([4]).numpy()
    paddle.seed(42)
    r2 = paddle.rand([4]).numpy()
    np.testing.assert_allclose(r1, r2)


def test_backward_inside_jit():
    """The tape must trace away under jax.jit — the dygraph facade's key property."""
    import jax

    def step(xv):
        x = paddle.Tensor(xv, stop_gradient=False)
        y = (x * x).sum()
        y.backward()
        return x.grad._value

    g = jax.jit(step)(np.array([1.0, 2.0], np.float32))
    np.testing.assert_allclose(np.asarray(g), [2.0, 4.0])


def test_topk():
    x = paddle.to_tensor([[1.0, 5.0, 3.0], [9.0, 2.0, 4.0]])
    vals, idx = paddle.topk(x, 2)
    np.testing.assert_allclose(vals.numpy(), [[5, 3], [9, 4]])
    np.testing.assert_array_equal(idx.numpy(), [[1, 2], [0, 2]])


def test_where_gather_scatter():
    x = paddle.to_tensor([1.0, 2.0, 3.0, 4.0])
    idx = paddle.to_tensor([0, 2])
    np.testing.assert_allclose(paddle.gather(x, idx).numpy(), [1, 3])
    cond = paddle.to_tensor([True, False, True, False])
    np.testing.assert_allclose(
        paddle.where(cond, x, -x).numpy(), [1, -2, 3, -4])


def test_pad_paddle_convention():
    # first pair pads the LAST dim (paddle convention)
    x = paddle.to_tensor(np.zeros((1, 1, 2, 3), "float32"))
    import paddle_tpu.nn.functional as F
    assert F.pad(x, [1, 0, 0, 0]).shape == [1, 1, 2, 4]
    assert F.pad(x, [0, 0, 1, 1]).shape == [1, 1, 4, 3]


def test_chunk_uneven_and_split_errors():
    c = paddle.chunk(paddle.to_tensor(np.arange(5.0)), 2)
    assert [t.shape[0] for t in c] == [3, 2]
    with pytest.raises(ValueError):
        paddle.split(paddle.to_tensor(np.arange(5.0)), 2)


def test_grad_does_not_touch_other_leaves():
    w = paddle.to_tensor([1.0], stop_gradient=False)
    x = paddle.to_tensor([2.0], stop_gradient=False)
    (gx,) = paddle.framework.grad((w * x).sum(), [x])
    assert w.grad is None
    np.testing.assert_allclose(gx.numpy(), [1.0])


def test_topk_grad_single_pass():
    t = paddle.to_tensor(np.array([3.0, 1.0, 2.0]), stop_gradient=False)
    vals, idx = paddle.topk(t, 2)
    vals.sum().backward()
    np.testing.assert_allclose(t.grad.numpy(), [1, 0, 1])
    np.testing.assert_array_equal(idx.numpy(), [0, 2])
