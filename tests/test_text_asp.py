"""viterbi_decode + ASP tests.

Oracles: a numpy dynamic-programming viterbi; ASP invariants (density, n:m
group checks, mask survival through decorated optimizer steps).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, ops, optimizer as opt
from paddle_tpu.text import viterbi_decode, ViterbiDecoder
from paddle_tpu.incubate import asp


def _np(t):
    return np.asarray(t._value)


def _np_viterbi(pot, trans, length, bos_eos):
    """Reference DP in plain numpy for one sequence."""
    T = pot.shape[-1]
    if bos_eos:
        alpha = pot[0] + trans[-1, :]
    else:
        alpha = pot[0].copy()
    bps = []
    for t in range(1, length):
        scores = alpha[:, None] + trans
        bps.append(scores.argmax(0))
        alpha = scores.max(0) + pot[t]
    if bos_eos:
        alpha = alpha + trans[:, -2]
    best = int(alpha.argmax())
    score = float(alpha.max())
    path = [best]
    for bp in reversed(bps):
        path.append(int(bp[path[-1]]))
    return score, list(reversed(path))


@pytest.mark.parametrize("bos_eos", [False, True])
def test_viterbi_matches_numpy(bos_eos):
    rng = np.random.default_rng(0)
    B, S, T = 3, 6, 5
    pot = rng.standard_normal((B, S, T)).astype(np.float32)
    trans = rng.standard_normal((T, T)).astype(np.float32)
    lens = np.array([6, 4, 1], np.int64)
    scores, paths = viterbi_decode(
        paddle.to_tensor(pot), paddle.to_tensor(trans),
        paddle.to_tensor(lens), include_bos_eos_tag=bos_eos)
    for b in range(B):
        ws, wp = _np_viterbi(pot[b], trans, int(lens[b]), bos_eos)
        assert abs(float(_np(scores)[b]) - ws) < 1e-4, b
        got = list(_np(paths)[b][:lens[b]])
        assert got == wp, (b, got, wp)
        assert (_np(paths)[b][lens[b]:] == 0).all()


def test_viterbi_decoder_layer():
    rng = np.random.default_rng(1)
    trans = paddle.to_tensor(rng.standard_normal((4, 4)).astype(np.float32))
    dec = ViterbiDecoder(trans, include_bos_eos_tag=False)
    pot = paddle.to_tensor(rng.standard_normal((2, 5, 4)).astype(np.float32))
    lens = paddle.to_tensor(np.array([5, 3], np.int64))
    scores, paths = dec(pot, lens)
    assert _np(scores).shape == (2,) and _np(paths).shape == (2, 5)


def test_asp_mask_and_density():
    rng = np.random.default_rng(2)
    w = rng.standard_normal((8, 16)).astype(np.float32)
    mask = asp.create_mask(w, n=2, m=4)
    assert asp.check_sparsity(w * mask, n=2, m=4)
    assert abs(asp.calculate_density(w * mask) - 0.5) < 1e-6
    # kept entries are the 2 largest |.| per group of 4
    g = np.abs(w.reshape(-1, 4))
    kept = (mask.reshape(-1, 4) == 1)
    for row_a, row_k in zip(g, kept):
        assert set(np.argsort(-row_a)[:2]) == set(np.flatnonzero(row_k))


def test_asp_prune_and_decorated_optimizer():
    paddle.seed(3)
    net = nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 4))
    pruned = asp.prune_model(net, n=2, m=4)
    assert len(pruned) == 2
    for l in (net._sub_layers["0"], net._sub_layers["2"]):
        assert asp.check_layer_sparsity(l)
    o = asp.decorate(opt.Adam(learning_rate=1e-2,
                              parameters=net.parameters()))
    x = paddle.to_tensor(np.random.default_rng(3)
                         .standard_normal((8, 16)).astype(np.float32))
    for _ in range(3):
        loss = ops.mean(net(x) ** 2)
        loss.backward()
        o.step()
        o.clear_grad()
    # masks survived the updates
    for l in (net._sub_layers["0"], net._sub_layers["2"]):
        assert asp.check_layer_sparsity(l)
        assert abs(asp.calculate_density(_np(l.weight)) - 0.5) < 1e-6
    asp.clear_masks()


def test_asp_conv_reduction_dim_and_scoping():
    asp.clear_masks()
    conv = nn.Conv2D(4, 8, 3)
    netc = nn.Sequential(conv)
    asp.prune_model(netc)
    # density exactly 0.5: grouping along in*kh*kw (36 % 4 == 0), not kw
    assert abs(asp.calculate_density(_np(conv.weight)) - 0.5) < 1e-6
    assert asp.check_layer_sparsity(conv)
    # decorated optimizer of another model must not touch conv's weights
    other = nn.Linear(4, 4)
    o = asp.decorate(opt.SGD(learning_rate=1.0,
                             parameters=other.parameters()))
    before = _np(conv.weight).copy()
    x = paddle.to_tensor(np.ones((2, 4), np.float32))
    loss = ops.mean(other(x) ** 2)
    loss.backward()
    o.step()
    np.testing.assert_allclose(_np(conv.weight), before)
    asp.clear_masks()


def test_hub_local_source(tmp_path):
    import paddle_tpu.hub as hub
    (tmp_path / "hubconf.py").write_text(
        "def tiny_model(scale=1):\n"
        "    '''a tiny model builder'''\n"
        "    return {'scale': scale}\n")
    assert hub.list(str(tmp_path), source="local") == ["tiny_model"]
    assert "tiny" in hub.help(str(tmp_path), "tiny_model", source="local")
    assert hub.load(str(tmp_path), "tiny_model", source="local",
                    scale=3) == {"scale": 3}
    with pytest.raises(RuntimeError):
        hub.load(str(tmp_path), "tiny_model", source="github")


def test_incubate_autotune_config(tmp_path):
    from paddle_tpu.incubate import autotune
    autotune.set_config({"dataloader": {"enable": True}})
    assert autotune.get_config()["dataloader"]["enable"]
    cfg_file = tmp_path / "at.json"
    cfg_file.write_text('{"kernel": {"enable": false}}')
    autotune.set_config(str(cfg_file))
    assert not autotune.get_config()["kernel"]["enable"]
    autotune.set_config(None)
    assert autotune.get_config()["kernel"]["enable"]
