"""paddle.utils (try_import/deprecated/unique_name/dlpack/require_version/
run_check), paddle.flops, paddle.onnx.export."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.utils import (deprecated, dlpack, require_version, run_check,
                              try_import, unique_name)


def test_try_import():
    assert try_import("math").sqrt(4) == 2.0
    with pytest.raises(ImportError, match="no_such_module_xyz"):
        try_import("no_such_module_xyz")
    with pytest.raises(ImportError, match="custom message"):
        try_import("no_such_module_xyz", "custom message")


def test_deprecated_levels():
    @deprecated(since="2.0", update_to="paddle.new_api", level=1)
    def old(x):
        return x + 1

    with pytest.warns(DeprecationWarning, match="new_api"):
        assert old(1) == 2

    @deprecated(level=2, reason="gone")
    def dead():
        pass

    with pytest.raises(RuntimeError, match="gone"):
        dead()

    @deprecated()  # level 0: marker only
    def fine(x):
        return x

    assert fine(3) == 3 and "deprecated" in fine.__doc__


def test_unique_name():
    with unique_name.guard():
        assert unique_name.generate("fc") == "fc_0"
        assert unique_name.generate("fc") == "fc_1"
        assert unique_name.generate("conv") == "conv_0"
        with unique_name.guard("block_"):
            assert unique_name.generate("fc") == "block_fc_0"
        assert unique_name.generate("fc") == "fc_2"


def test_require_version():
    require_version("0.0.1")  # current 0.1.0 >= 0.0.1
    with pytest.raises(Exception):
        require_version("999.0.0")
    with pytest.raises(ValueError):
        require_version("not-a-version")


def test_run_check(capsys):
    run_check()
    out = capsys.readouterr().out
    assert "installed successfully" in out


def test_dlpack_roundtrip():
    t = paddle.to_tensor(np.arange(6, dtype=np.float32).reshape(2, 3))
    cap = dlpack.to_dlpack(t)
    back = dlpack.from_dlpack(cap)
    np.testing.assert_allclose(
        np.asarray(back.numpy()),
        np.arange(6, dtype=np.float32).reshape(2, 3))
    # torch interop (torch tensors speak __dlpack__)
    torch = pytest.importorskip("torch")
    tt = torch.arange(4, dtype=torch.float32)
    back2 = dlpack.from_dlpack(tt)
    np.testing.assert_allclose(np.asarray(back2.numpy()), [0, 1, 2, 3])


def test_flops_linear_and_conv():
    net = nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 4))
    n = paddle.flops(net, [2, 16])
    # MACs: 2*16*32 + 2*32*4 = 1024 + 256
    assert n == 2 * 16 * 32 + 2 * 32 * 4, n

    conv = nn.Sequential(nn.Conv2D(3, 8, 3, padding=1), nn.ReLU6())
    n2 = paddle.flops(conv, [1, 3, 8, 8], print_detail=True)
    # out numel (1*8*8*8) * (in_c/groups * k*k + bias)
    assert n2 == 8 * 8 * 8 * (3 * 9 + 1), n2


def test_flops_dedup_warn_and_subclass():
    # weight tying: the same Layer object under two names counts once
    shared = nn.Linear(8, 8)
    net = nn.Sequential(shared, shared)
    assert paddle.flops(net, [1, 8]) == 2 * (8 * 8)

    # subclass of a covered type still counts via the isinstance walk
    class MyLinear(nn.Linear):
        pass

    assert paddle.flops(MyLinear(4, 4), [1, 4]) == 4 * 4

    # uncovered parametered layer warns instead of silently undercounting
    class Weird(nn.Layer):
        def __init__(self):
            super().__init__()
            self.w = self.create_parameter([3])

        def forward(self, x):
            return x

    with pytest.warns(UserWarning, match="zero FLOPs"):
        paddle.flops(Weird(), [2, 3])


def test_download_md5_gate(tmp_path):
    from paddle_tpu.utils import download
    f = tmp_path / "w.bin"
    f.write_bytes(b"abc")
    import hashlib
    good = hashlib.md5(b"abc", usedforsecurity=False).hexdigest()
    p = download.get_path_from_url("http://x/w.bin", root_dir=str(tmp_path),
                                   md5sum=good)
    assert p == str(f)
    with pytest.raises(RuntimeError, match="md5"):
        download.get_path_from_url("http://x/w.bin", root_dir=str(tmp_path),
                                   md5sum="0" * 32)
    with pytest.raises(RuntimeError, match="egress"):
        download.get_path_from_url("http://x/missing.bin",
                                   root_dir=str(tmp_path))


def test_flops_tied_parameter_counts_once(capsys):
    # two distinct Linear layers sharing ONE Parameter (classic weight
    # tying); sized so dedup (1.00M) vs double-count (2.00M) actually
    # differ in the printed 2-decimal total
    a = nn.Linear(1000, 1000)
    b = nn.Linear(1000, 1000)
    b.weight = a.weight
    net = nn.Sequential(a, b)
    paddle.flops(net, [1, 1000], print_detail=True)
    out = capsys.readouterr().out
    assert f"{(1000 * 1000 + 1000 + 1000) / 1e6:.2f}M" in out, out


def test_flops_custom_ops():
    class Doubler(nn.Layer):
        def forward(self, x):
            return x * 2

    def count_doubler(m, x, y):
        m.total_ops += 1234

    net = Doubler()
    assert paddle.flops(net, [4, 4], custom_ops={Doubler: count_doubler}) \
        == 1234


def test_onnx_export_requires_paddle2onnx(tmp_path):
    net = nn.Linear(4, 2)
    with pytest.raises(ImportError, match="StableHLO"):
        paddle.onnx.export(net, str(tmp_path / "m"))
    with pytest.raises(ValueError, match="file_prefix is empty"):
        paddle.onnx.export(net, str(tmp_path) + "/")


# ------------------------------------------------------- custom op registry
def test_custom_op_registration_and_grad():
    """phi/capi analog: a registered pure-jax op dispatches through the
    tape (eager + backward + Tensor method + static capture)."""
    import jax.numpy as jnp
    from paddle_tpu.utils.custom_op import register_op, list_custom_ops

    @register_op("swishy")
    def swishy(x, beta=1.0):
        return x * (1.0 / (1.0 + jnp.exp(-beta * x)))

    assert "swishy" in list_custom_ops()
    x = paddle.to_tensor(np.array([0.5, -1.0], np.float32))
    x.stop_gradient = False
    y = paddle.ops.swishy(x, beta=2.0)
    ref = x.numpy() / (1 + np.exp(-2.0 * x.numpy()))
    np.testing.assert_allclose(y.numpy(), ref, rtol=1e-6)
    y.sum().backward()
    assert x.grad is not None and np.isfinite(x.grad.numpy()).all()
    # tensor method + top-level surface
    np.testing.assert_allclose(
        paddle.to_tensor(ref).swishy().numpy(),
        ref / (1 + np.exp(-ref)), rtol=1e-6)

    # static capture routes through the same dispatch
    from paddle_tpu import static
    static.enable_static()
    try:
        main = static.Program()
        with static.program_guard(main):
            v = static.data("v", [2], "float32")
            out = paddle.ops.swishy(v)
        got = static.Executor().run(main, feed={"v": x.numpy()},
                                    fetch_list=[out])[0]
        np.testing.assert_allclose(
            got, x.numpy() / (1 + np.exp(-x.numpy())), rtol=1e-6)
    finally:
        static.disable_static()


def test_custom_op_custom_vjp():
    """bwd= slot: a hand-written backward (the Pallas-kernel plug point)."""
    import jax.numpy as jnp
    from paddle_tpu.utils.custom_op import register_op

    def bwd(res, cot):
        (xv,) = res
        return (cot * 3.0 * xv * xv,)  # d(x^3)

    @register_op("cubed_custom", bwd=bwd)
    def cubed_custom(x):
        return x ** 3

    x = paddle.to_tensor(np.array([2.0], np.float32))
    x.stop_gradient = False
    paddle.ops.cubed_custom(x).sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [12.0], rtol=1e-6)

    with pytest.raises(ValueError, match="already registered"):
        register_op("cubed_custom", lambda x: x)


def test_custom_op_vjp_kwargs_and_partial_diff():
    """bwd ops accept kwargs (static per-signature) and n_diff_args pads
    the non-diff tail's cotangents."""
    import jax.numpy as jnp
    from paddle_tpu.utils.custom_op import register_op

    def bwd(res, cot):
        (xv,) = res
        return (cot * 2.0 * xv,)

    @register_op("sq_scaled", bwd=bwd, n_diff_args=1)
    def sq_scaled(x, s, gain=1.0):
        return gain * x * x + 0.0 * s.sum()

    x = paddle.to_tensor(np.array([3.0], np.float32))
    x.stop_gradient = False
    s = paddle.to_tensor(np.array([1.0], np.float32))
    out = paddle.ops.sq_scaled(x, s, gain=2.0)
    np.testing.assert_allclose(out.numpy(), [18.0], rtol=1e-6)
    out.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [6.0], rtol=1e-6)
