"""paddle.vision.ops detection operators vs numpy oracles."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.vision import ops as V


def _np_nms(boxes, scores, thresh):
    order = np.argsort(-scores)
    keep = []
    sup = np.zeros(len(boxes), bool)
    for i in order:
        if sup[i]:
            continue
        keep.append(i)
        for j in order:
            if sup[j] or j == i:
                continue
            # iou
            x1 = max(boxes[i, 0], boxes[j, 0])
            y1 = max(boxes[i, 1], boxes[j, 1])
            x2 = min(boxes[i, 2], boxes[j, 2])
            y2 = min(boxes[i, 3], boxes[j, 3])
            inter = max(x2 - x1, 0) * max(y2 - y1, 0)
            a = (boxes[i, 2] - boxes[i, 0]) * (boxes[i, 3] - boxes[i, 1])
            b = (boxes[j, 2] - boxes[j, 0]) * (boxes[j, 3] - boxes[j, 1])
            if inter / (a + b - inter) > thresh:
                sup[j] = True
    return np.array(keep)


def test_nms_matches_numpy_oracle():
    rng = np.random.default_rng(0)
    n = 32
    xy = rng.uniform(0, 50, (n, 2)).astype(np.float32)
    wh = rng.uniform(5, 25, (n, 2)).astype(np.float32)
    boxes = np.concatenate([xy, xy + wh], 1)
    scores = rng.uniform(0, 1, n).astype(np.float32)
    kept = np.asarray(V.nms(paddle.to_tensor(boxes), 0.4,
                            paddle.to_tensor(scores)).numpy())
    want = _np_nms(boxes, scores, 0.4)
    np.testing.assert_array_equal(kept, want)
    # top_k cap
    kept2 = np.asarray(V.nms(paddle.to_tensor(boxes), 0.4,
                             paddle.to_tensor(scores), top_k=3).numpy())
    np.testing.assert_array_equal(kept2, want[:3])


def test_nms_per_category():
    boxes = np.array([[0, 0, 10, 10], [1, 1, 11, 11],
                      [0, 0, 10, 10]], np.float32)
    scores = np.array([0.9, 0.8, 0.7], np.float32)
    cats = np.array([0, 0, 1], np.int64)
    kept = np.asarray(V.nms(paddle.to_tensor(boxes), 0.5,
                            paddle.to_tensor(scores),
                            category_idxs=paddle.to_tensor(cats),
                            categories=[0, 1]).numpy())
    # box1 suppressed by box0 (same cat); box2 survives (different cat)
    np.testing.assert_array_equal(sorted(kept), [0, 2])


def test_roi_align_uniform_feature():
    # constant feature map -> every pooled value equals the constant
    x = np.full((1, 3, 16, 16), 7.0, np.float32)
    boxes = np.array([[2.0, 2.0, 10.0, 10.0]], np.float32)
    out = V.roi_align(paddle.to_tensor(x), paddle.to_tensor(boxes),
                      paddle.to_tensor(np.array([1], np.int32)), 4)
    o = np.asarray(out.numpy())
    assert o.shape == (1, 3, 4, 4)
    np.testing.assert_allclose(o, 7.0, rtol=1e-5)


def test_roi_align_linear_gradient_field():
    # f(x, y) = x: pooled bin centers must read back their x coordinate
    H = W = 16
    x = np.tile(np.arange(W, dtype=np.float32), (H, 1))[None, None]
    boxes = np.array([[4.0, 4.0, 12.0, 12.0]], np.float32)
    out = V.roi_align(paddle.to_tensor(x), paddle.to_tensor(boxes),
                      paddle.to_tensor(np.array([1], np.int32)), 2,
                      aligned=False)
    o = np.asarray(out.numpy())[0, 0]
    # bins span [4,8] and [8,12] in x: centers 6 and 10
    np.testing.assert_allclose(o[:, 0], 6.0, atol=0.6)
    np.testing.assert_allclose(o[:, 1], 10.0, atol=0.6)


def test_roi_pool_max_semantics():
    x = np.zeros((1, 1, 8, 8), np.float32)
    x[0, 0, 2, 2] = 5.0
    x[0, 0, 6, 6] = 9.0
    boxes = np.array([[0.0, 0.0, 7.0, 7.0]], np.float32)
    out = V.roi_pool(paddle.to_tensor(x), paddle.to_tensor(boxes),
                     paddle.to_tensor(np.array([1], np.int32)), 2)
    o = np.asarray(out.numpy())[0, 0]
    assert o[0, 0] == 5.0 and o[1, 1] == 9.0


def test_box_coder_encode_decode_roundtrip():
    priors = np.array([[10, 10, 30, 30], [5, 5, 15, 25]], np.float32)
    pvar = np.ones((2, 4), np.float32)
    targets = np.array([[12, 8, 33, 35]], np.float32)
    enc = V.box_coder(paddle.to_tensor(priors), paddle.to_tensor(pvar),
                      paddle.to_tensor(targets), "encode_center_size")
    assert tuple(enc.shape) == (1, 2, 4)  # [targets, priors, 4]
    # priors lie along dim 1 of enc -> axis=1
    dec = V.box_coder(paddle.to_tensor(priors), paddle.to_tensor(pvar),
                      enc, "decode_center_size", axis=1)
    d = np.asarray(dec.numpy())
    np.testing.assert_allclose(d[0, 0], targets[0], rtol=1e-5)
    np.testing.assert_allclose(d[0, 1], targets[0], rtol=1e-5)
    # axis=0: same codes transposed to [priors, targets, 4]
    enc_t = paddle.to_tensor(
        np.transpose(np.asarray(enc.numpy()), (1, 0, 2)))
    dec0 = V.box_coder(paddle.to_tensor(priors), paddle.to_tensor(pvar),
                       enc_t, "decode_center_size", axis=0)
    d0 = np.asarray(dec0.numpy())
    np.testing.assert_allclose(d0[0, 0], targets[0], rtol=1e-5)
    np.testing.assert_allclose(d0[1, 0], targets[0], rtol=1e-5)


def test_yolo_box_box_score_alignment():
    # one very confident cell at (h=0, w=1) on a 1x2x3 grid: the flat index
    # of its nonzero box must equal the flat index of its nonzero score
    A, C, H, W = 1, 2, 2, 3
    x = np.full((1, A * (5 + C), H, W), -12.0, np.float32)
    x[0, 4, 0, 1] = 12.0   # objectness at that cell
    x[0, 5, 0, 1] = 12.0   # class 0 prob
    boxes, scores = V.yolo_box(paddle.to_tensor(x),
                               paddle.to_tensor(np.array([[32, 32]],
                                                         np.int32)),
                               anchors=[10, 13], class_num=C,
                               conf_thresh=0.5, downsample_ratio=16)
    b = np.asarray(boxes.numpy())[0]
    s = np.asarray(scores.numpy())[0]
    box_idx = np.flatnonzero(np.abs(b).sum(-1) > 0)
    score_idx = np.flatnonzero(s.sum(-1) > 0.5)
    np.testing.assert_array_equal(box_idx, score_idx)
    assert box_idx.tolist() == [0 * W + 1]  # (h=0, w=1) h-major


def test_roi_pool_outside_bins_are_zero():
    x = np.ones((1, 1, 8, 8), np.float32)
    boxes = np.array([[-6.0, -6.0, 1.0, 1.0]], np.float32)
    out = np.asarray(V.roi_pool(
        paddle.to_tensor(x), paddle.to_tensor(boxes),
        paddle.to_tensor(np.array([1], np.int32)), 2).numpy())[0, 0]
    assert out[1, 1] == 1.0          # in-image bin
    assert (out[:1, :] == 0).all() and out[1, 0] == 0  # outside bins: 0
    assert np.isfinite(out).all()


def test_yolo_box_shapes_and_range():
    rng = np.random.default_rng(0)
    A, C, H, W = 2, 4, 3, 3
    x = rng.standard_normal((2, A * (5 + C), H, W)).astype(np.float32)
    img = np.array([[32, 32], [64, 48]], np.int32)
    boxes, scores = V.yolo_box(paddle.to_tensor(x), paddle.to_tensor(img),
                               anchors=[10, 13, 16, 30], class_num=C,
                               conf_thresh=0.0, downsample_ratio=8)
    b = np.asarray(boxes.numpy())
    s = np.asarray(scores.numpy())
    assert b.shape == (2, A * H * W, 4) and s.shape == (2, A * H * W, C)
    assert (s >= 0).all() and (s <= 1).all()
    assert (b[..., 2] >= b[..., 0] - 1e-3).all()


def test_nms_rejects_static_capture():
    from paddle_tpu import static
    static.enable_static()
    try:
        main = static.Program()
        with static.program_guard(main):
            b = static.data("b", [8, 4], "float32")
            with pytest.raises(RuntimeError, match="dygraph"):
                V.nms(b, 0.4)
    finally:
        static.disable_static()


def test_yolo_box_iou_aware_not_supported():
    with pytest.raises(NotImplementedError, match="iou_aware"):
        V.yolo_box(paddle.to_tensor(np.zeros((1, 16, 2, 2), np.float32)),
                   paddle.to_tensor(np.array([[32, 32]], np.int32)),
                   anchors=[10, 13], class_num=2, conf_thresh=0.1,
                   downsample_ratio=16, iou_aware=True)


def test_conv_norm_activation_block():
    blk = V.ConvNormActivation(3, 8, kernel_size=3)
    x = paddle.to_tensor(np.random.default_rng(0)
                         .standard_normal((2, 3, 8, 8)).astype(np.float32))
    y = blk(x)
    assert tuple(y.shape) == (2, 8, 8, 8)
    assert float(paddle.min(y).numpy()) >= 0.0  # ReLU applied
