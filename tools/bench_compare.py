"""Diff two ``BENCH_rNN.json`` artifacts, anchored on predicted rows.

The driver's bench rounds run in a container whose CPU allotment varies
~40% run to run, so raw measured deltas are mostly noise. Two row
classes therefore get different treatment:

- ``*_predicted`` rows come from the static cost model: **zero run-to-run
  noise**, so ANY worsening beyond a tight threshold (default 2%) is a
  real modelled regression — the code got slower/bigger, not the box.
- measured rows use a wide threshold (default 40%, the observed
  container variance); additionally, when a measured row has a matching
  predicted anchor (``gpt_345m_tokens_per_sec_per_chip`` ↔
  ``gpt_345m_predicted``), the report shows the anchor-normalized ratio
  (measured / predicted), the number that SHOULD be environment-stable.

Rows whose unit marks them non-metrics (skipped / error / timeout /
info) are ignored, as are ``*_cpu_smoke`` vs TPU mismatches (a CPU
fallback round never regresses a TPU number).

Every row carries a ``calibration_id`` in its extras (hash of the
active ``calibration.json``, or ``"default"``). A measured row is only
anchor-normalized against a predicted row produced under the SAME
calibration — a refit changes what "predicted" means, so crossing ids
would book the calibration delta as an environment drift. Refused
anchors are reported per-row (``anchor_refused``), never silently
dropped.

Exit codes: 0 = no regressions, 1 = regression(s) beyond threshold,
2 = artifact unreadable.

Usage::

    python tools/bench_compare.py BENCH_r03.json BENCH_r06.json
    python tools/bench_compare.py A.json B.json --threshold 0.3 --json
"""
import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_NON_METRIC_UNITS = {"skipped", "error", "timeout", "info"}
# metrics where a LOWER value is the improvement
_LOWER_IS_BETTER_MARKERS = ("decode_ms", "peak_hbm", "step_ms", "latency")


def load_rows(path) -> dict:
    """``{metric: row}`` from one driver artifact (``tail`` lines +
    ``parsed``) or from a bare JSONL of bench rows. Later lines win."""
    rows = {}
    with open(path) as f:
        doc = json.load(f)
    if isinstance(doc, dict) and ("tail" in doc or "parsed" in doc):
        lines = str(doc.get("tail", "")).splitlines()
        if isinstance(doc.get("parsed"), dict):
            lines.append(json.dumps(doc["parsed"]))
    elif isinstance(doc, list):
        lines = [json.dumps(r) for r in doc]
    else:
        lines = [json.dumps(doc)]
    for ln in lines:
        ln = ln.strip()
        if not (ln.startswith("{") and '"metric"' in ln):
            continue
        try:
            rec = json.loads(ln)
        except ValueError:
            continue
        metric = rec.get("metric")
        if not isinstance(metric, str):
            continue
        if str(rec.get("unit", "")).lower() in _NON_METRIC_UNITS:
            continue
        if metric.endswith(("_SKIPPED", "_ERROR", "_TIMEOUT", "_FALLBACK")):
            continue
        if not isinstance(rec.get("value"), (int, float)) \
                or rec["value"] <= 0:
            continue
        rows[metric] = rec
    return rows


def _lower_is_better(metric, row):
    u = str(row.get("unit", "")).lower()
    return any(m in metric for m in _LOWER_IS_BETTER_MARKERS) \
        or u.startswith(("ms", "gib", "gb", "s/"))


# measured metric -> its predicted anchor, where the suffix rule below
# doesn't apply (serving + quantized-collective rows)
_ANCHOR_MAP = {
    "serving_engine_tokens_per_sec": "serving_predicted",
    "serving_engine_int8_tokens_per_sec": "serving_int8_predicted",
    "serving_shared_prefix": "serving_shared_prefix_predicted",
    "serving_disagg": "serving_disagg_predicted",
    # the MoE serving engine row (ERNIE-MoE, fused Pallas dispatch)
    # anchors on the static cost model's MoE decode-program row
    "serving_moe_tokens_per_sec": "serving_moe_predicted",
    "serving_moe": "serving_moe_predicted",
    # the N-replica fleet row anchors on the fleet roofline model
    # (per-replica roofline x N minus router overhead)
    "serving_fleet_tokens_per_sec": "serving_fleet_predicted",
    "serving_fleet": "serving_fleet_predicted",
    # a future measured live-migration row (ms per moved request /
    # resume speedup) anchors on the payload-over-interconnect model
    "serving_fleet_migration": "serving_fleet_migration_predicted",
    "serving_fleet_migration_ms": "serving_fleet_migration_predicted",
    # the overload-control A/B (deadline-met goodput at 2x-capacity
    # arrival) anchors on the control-vs-FIFO roofline model
    "serving_overload": "serving_overload_predicted",
    "serving_overload_goodput_tokens_per_sec":
        "serving_overload_predicted",
    "collective_compression": "collective_compression_predicted",
    # future measured auto-fusion rows (per-rule step-ms saved on TPU)
    # anchor on the rewrite pass's predicted per-rule Δstep-ms rows
    "autofusion": "autofusion_predicted",
    "autofusion_ms_saved": "autofusion_predicted",
    "autofusion_int8_dequant_matmul":
        "autofusion_int8_dequant_matmul_predicted",
    "autofusion_ragged_prefill": "autofusion_ragged_prefill_predicted",
    "autofusion_moe_gate_dispatch":
        "autofusion_moe_gate_dispatch_predicted",
    # a measured planner-config 13B run (TPU rounds) anchors on the
    # planner's own predicted row, not the hand-written config's
    "gpt_13b_planned_tokens_per_sec_per_chip": "gpt_13b_planned_predicted",
}


def _calibration_of(row) -> str:
    """The calibration id a row was produced under. Rows predate the
    stamp or were emitted with no calibration active → "default"."""
    extras = row.get("extras") or {}
    return str(extras.get("calibration_id")
               or row.get("calibration_id") or "default")


def _predicted_anchor(metric, rows):
    """The *_predicted row anchoring a measured metric, if present
    (gpt_345m_tokens_per_sec_per_chip -> gpt_345m_predicted;
    serving/collective rows via the explicit map)."""
    base = metric[:-len("_cpu_smoke")] if metric.endswith("_cpu_smoke") \
        else metric
    if base in _ANCHOR_MAP:
        return rows.get(_ANCHOR_MAP[base])
    for cut in ("_tokens_per_sec_per_chip", "_imgs_per_sec_per_chip"):
        if metric.endswith(cut):
            return rows.get(metric[: -len(cut)] + "_predicted")
    return None


def compare(rows_a: dict, rows_b: dict, threshold=0.40,
            predicted_threshold=0.02) -> dict:
    """Per-metric deltas + regression verdicts between two row maps."""
    out = {"metrics": [], "regressions": [], "only_a": [], "only_b": []}
    out["only_a"] = sorted(set(rows_a) - set(rows_b))
    out["only_b"] = sorted(set(rows_b) - set(rows_a))
    for metric in sorted(set(rows_a) & set(rows_b)):
        a, b = rows_a[metric], rows_b[metric]
        va, vb = float(a["value"]), float(b["value"])
        change = (vb - va) / va
        predicted = metric.endswith("_predicted") or "_predicted_" in metric
        lower_better = _lower_is_better(metric, b)
        worsening = change > 0 if lower_better else change < 0
        limit = predicted_threshold if predicted else threshold
        regression = worsening and abs(change) > limit
        rec = {
            "metric": metric, "a": va, "b": vb,
            "change_pct": round(100 * change, 2),
            "predicted": predicted, "lower_is_better": lower_better,
            "regression": regression, "threshold_pct": round(100 * limit, 1),
        }
        anchor_a = _predicted_anchor(metric, rows_a)
        anchor_b = _predicted_anchor(metric, rows_b)
        if anchor_a and anchor_b and not predicted:
            mismatch = [
                f"{side} measured={_calibration_of(row)} "
                f"anchor={_calibration_of(anchor)}"
                for side, row, anchor in (("A", a, anchor_a),
                                          ("B", b, anchor_b))
                if _calibration_of(row) != _calibration_of(anchor)]
            if mismatch:
                # predicted constants differ from the ones active when
                # the measurement ran — the ratio would mix a refit into
                # the environment story; refuse, visibly
                rec["anchor_refused"] = ("calibration mismatch: "
                                         + "; ".join(mismatch))
            else:
                # measured/predicted: the environment-independent view —
                # predicted rows absorb intentional model/config changes
                na = va / float(anchor_a["value"])
                nb = vb / float(anchor_b["value"])
                rec["anchored_ratio_a"] = round(na, 4)
                rec["anchored_ratio_b"] = round(nb, 4)
                rec["anchored_change_pct"] = round(100 * (nb - na) / na, 2)
        out["metrics"].append(rec)
        if regression:
            out["regressions"].append(rec)
    return out


def format_table(result) -> str:
    lines = [f"{'metric':<46} {'A':>12} {'B':>12} {'Δ%':>8}  verdict"]
    lines.append("-" * len(lines[0]))
    for rec in result["metrics"]:
        verdict = "REGRESSION" if rec["regression"] else (
            "anchor" if rec["predicted"] else "ok")
        extra = ""
        if "anchored_change_pct" in rec:
            extra = f"  (vs-predicted {rec['anchored_change_pct']:+.1f}%)"
        elif "anchor_refused" in rec:
            extra = f"  (anchor refused: {rec['anchor_refused']})"
        lines.append(
            f"{rec['metric']:<46} {rec['a']:>12.1f} {rec['b']:>12.1f} "
            f"{rec['change_pct']:>+7.1f}%  {verdict}{extra}")
    for side, label in (("only_a", "only in A"), ("only_b", "only in B")):
        for m in result[side]:
            lines.append(f"{m:<46} {label}")
    n = len(result["regressions"])
    lines.append(f"{n} regression(s) beyond threshold"
                 if n else "no regressions beyond threshold")
    return "\n".join(lines)


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="diff two bench artifacts; predicted rows are "
                    "noise-free anchors, exit 1 on regression")
    ap.add_argument("artifact_a", help="older BENCH_rNN.json")
    ap.add_argument("artifact_b", help="newer BENCH_rNN.json")
    ap.add_argument("--threshold", type=float, default=0.40,
                    help="measured-row regression threshold (fraction; "
                         "default 0.40 ≈ container CPU variance)")
    ap.add_argument("--predicted-threshold", type=float, default=0.02,
                    help="predicted-row regression threshold (fraction)")
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)
    try:
        rows_a, rows_b = load_rows(args.artifact_a), load_rows(args.artifact_b)
    except (OSError, ValueError) as e:
        print(f"bench_compare: cannot read artifact: {e}", file=sys.stderr)
        return 2
    result = compare(rows_a, rows_b, threshold=args.threshold,
                     predicted_threshold=args.predicted_threshold)
    if args.json:
        print(json.dumps(result, indent=2, sort_keys=True))
    else:
        print(format_table(result))
    return 1 if result["regressions"] else 0


if __name__ == "__main__":
    sys.exit(main())
