#!/usr/bin/env python
"""Self-lint CLI for the host concurrency sanitizer
(``paddle_tpu.analysis.concurrency``) — the verify-skill gate.

    python tools/check_concurrency.py paddle_tpu
    python tools/check_concurrency.py paddle_tpu --json
    python tools/check_concurrency.py path/to/file.py other/dir

Exit codes:
    0  clean — zero unsuppressed findings of ANY severity (infos
       included: every finding on the tree must be fixed or carry an
       inline ``# ptcy: allow(...)`` justification)
    1  findings remain
    2  the linter itself crashed

Suppressed (allowlisted) findings are always printed with their
justification — an audited exception is visible, never silent.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Host concurrency sanitizer (PTCY001-005)")
    ap.add_argument("paths", nargs="*", default=None,
                    help="files/dirs to lint (default: the paddle_tpu "
                         "package next to this script)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="one JSON object on stdout")
    ap.add_argument("--errors-only", action="store_true",
                    help="print (and gate on) errors only")
    args = ap.parse_args(argv)

    paths = args.paths or [os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "paddle_tpu")]
    # bare package name -> directory next to the repo root
    paths = [p if os.path.exists(p) else
             os.path.join(os.path.dirname(os.path.dirname(
                 os.path.abspath(__file__))), p)
             for p in paths]

    from paddle_tpu.analysis.concurrency import lint_paths
    active, suppressed = lint_paths(paths)
    if args.errors_only:
        active = [d for d in active if d.severity == "error"]

    def row(d):
        return {"code": d.code, "severity": d.severity,
                "file": os.path.relpath(d.file) if d.file else None,
                "line": d.line, "message": d.message,
                "suppressed": bool(d.extra.get("suppressed")),
                "justification": d.extra.get("justification"),
                "extra": {k: v for k, v in d.extra.items()
                          if k not in ("suppressed", "justification")
                          and isinstance(v, (str, int, float, bool,
                                             list, dict, type(None)))}}

    if args.as_json:
        print(json.dumps({
            "clean": not active,
            "counts": {
                "error": sum(d.severity == "error" for d in active),
                "warning": sum(d.severity == "warning" for d in active),
                "info": sum(d.severity == "info" for d in active),
                "suppressed": len(suppressed)},
            "findings": [row(d) for d in active],
            "suppressed": [row(d) for d in suppressed]}))
    else:
        for d in active:
            loc = f"{os.path.relpath(d.file)}:{d.line}" if d.file \
                else "<?>"
            print(f"[{d.severity.upper()}] {d.code} {loc}: {d.message}")
        for d in suppressed:
            loc = f"{os.path.relpath(d.file)}:{d.line}" if d.file \
                else "<?>"
            print(f"[allowed] {d.code} {loc}: {d.message}")
            print(f"          justification: "
                  f"{d.extra.get('justification')}")
        n = len(active)
        print(f"{n} finding(s), {len(suppressed)} allowlisted "
              f"({'clean' if not n else 'NOT clean'})")
    return 0 if not active else 1


if __name__ == "__main__":
    try:
        sys.exit(main())
    except SystemExit:
        raise
    except Exception as exc:  # harness crash, not a lint failure
        print(f"check_concurrency: internal error: {exc!r}",
              file=sys.stderr)
        import traceback
        traceback.print_exc()
        sys.exit(2)
