#!/usr/bin/env python
"""Static program check: lint models/train steps before they hit XLA.

Runs the :mod:`paddle_tpu.analysis` pass suite (recompile hazards, host
syncs, collective-schedule consistency, AMP cast audit, dead code, the
cost/roofline model, the liveness peak-HBM estimator, and the
buffer-donation sanitizer) over the built-in model zoo — each model is
linted TWICE: the eager train-step closure (abstract tape trace → jaxpr
passes) and the recorded ``static.Program`` DAG (deadcode + AMP node
audit). No device execution: tiny configs, abstract shapes only.

``--hbm-budget-gb`` (default 16, the chip) arms the PTMM001
OOM-before-compile gate: a model whose predicted peak HBM exceeds the
budget — or any PTBD001 use-after-donate — fails the gate even under
``--errors-only``.

Usage::

    python tools/check_program.py                  # all models
    python tools/check_program.py --model gpt      # one model
    python tools/check_program.py --json           # machine-readable
    python tools/check_program.py --errors-only    # warnings don't fail

Exit code: 0 iff every report is CLEAN (no errors, no warnings —
matching ``Report.clean``; ``--errors-only`` relaxes to errors), 1
otherwise, 2 on a harness crash. Diagnostics also land in
runlog (``analysis_diagnostic`` events) when ``PADDLE_TELEMETRY_DIR`` is
set — the observability docs' diagnostics-as-runlog-events contract.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _force_platform():
    """Honor JAX_PLATFORMS even where a sitecustomize force-selects the
    TPU via jax.config (the env var alone is ignored there)."""
    plat = os.environ.get("JAX_PLATFORMS")
    if plat:
        import jax
        jax.config.update("jax_platforms", plat.split(",")[0])


# ---------------------------------------------------------------------------
# model-zoo targets (tiny configs — the lint is abstract, keep builds fast)
# ---------------------------------------------------------------------------

def _lint_static(build, name, world_size=None, hbm_budget_gb=None):
    """Record ``build()`` into a fresh Program (with per-node source
    sites) and run the DAG passes over it."""
    from paddle_tpu import static
    from paddle_tpu.analysis import ProgramAnalyzer
    static.enable_static()
    try:
        prog = static.Program()
        prog._capture_sites = True
        with static.program_guard(prog):
            fetches = build()
        return ProgramAnalyzer(
            world_size=world_size, hbm_budget_gb=hbm_budget_gb).analyze(
            prog, fetch_list=list(fetches), name=name)
    finally:
        static.disable_static()


def lint_gpt(world_size=None, hbm_budget_gb=None):
    import jax
    import jax.numpy as jnp
    import paddle_tpu as paddle
    from paddle_tpu import static
    from paddle_tpu.analysis import ProgramAnalyzer
    from paddle_tpu.models.gpt import (GPTForPretraining, GPTModel,
                                       GPTPretrainingCriterion,
                                       gpt_tiny_config)
    paddle.seed(0)
    cfg = gpt_tiny_config()
    model = GPTForPretraining(GPTModel(cfg))
    crit = GPTPretrainingCriterion()
    B, S = 2, 16
    ids = jax.ShapeDtypeStruct((B, S), jnp.int32)
    reports = [ProgramAnalyzer(
        world_size=world_size, hbm_budget_gb=hbm_budget_gb).analyze(
        lambda i, l: crit(model(i), l), ids, ids, name="gpt.train_step")]

    def build():
        fids = static.data("ids", [B, S], "int64")
        labels = static.data("labels", [B, S], "int64")
        loss = crit(model(fids), labels)
        return [loss]

    reports.append(_lint_static(build, "gpt.program", world_size,
                                hbm_budget_gb))
    return reports


def lint_bert(world_size=None, hbm_budget_gb=None):
    import jax
    import jax.numpy as jnp
    import paddle_tpu as paddle
    from paddle_tpu import static
    from paddle_tpu.analysis import ProgramAnalyzer
    from paddle_tpu.models.bert import (BertForPretraining, BertModel,
                                        bert_tiny_config)
    paddle.seed(0)
    model = BertForPretraining(BertModel(bert_tiny_config()))
    B, S = 2, 16
    ids = jax.ShapeDtypeStruct((B, S), jnp.int64)
    reports = [ProgramAnalyzer(
        world_size=world_size, hbm_budget_gb=hbm_budget_gb).analyze(
        lambda i, l: model.forward_with_mlm_loss(i, l), ids, ids,
        name="bert.train_step")]

    def build():
        fids = static.data("ids", [B, S], "int64")
        labels = static.data("labels", [B, S], "int64")
        return [model.forward_with_mlm_loss(fids, labels)]

    reports.append(_lint_static(build, "bert.program", world_size,
                                hbm_budget_gb))
    return reports


def lint_ernie_moe(world_size=None, hbm_budget_gb=None):
    import jax
    import jax.numpy as jnp
    import paddle_tpu as paddle
    from paddle_tpu import static
    from paddle_tpu.analysis import ProgramAnalyzer
    from paddle_tpu.models import (ErnieMoeForPretraining, ErnieMoeModel,
                                   ernie_moe_tiny_config)
    paddle.seed(0)
    model = ErnieMoeForPretraining(
        ErnieMoeModel(ernie_moe_tiny_config(num_hidden_layers=2)))
    B, S = 2, 16
    ids = jax.ShapeDtypeStruct((B, S), jnp.int64)
    reports = [ProgramAnalyzer(
        world_size=world_size, hbm_budget_gb=hbm_budget_gb).analyze(
        lambda i, l: model.forward_with_mlm_loss(i, l), ids, ids,
        name="ernie_moe.train_step")]

    def build():
        fids = static.data("ids", [B, S], "int64")
        labels = static.data("labels", [B, S], "int64")
        return [model.forward_with_mlm_loss(fids, labels)]

    reports.append(_lint_static(build, "ernie_moe.program",
                                world_size, hbm_budget_gb))
    return reports


def lint_serving(world_size=None, hbm_budget_gb=None):
    """Serving decode gate: (1) the pass suite over the engine's decode
    step (collective schedule stays clean — no rank-divergent ops hide
    in the serving path), and (2) the recompile proof — replay a
    randomized admission mix through the REAL continuous-batching
    scheduler (device-free shape probe) and require every decode/prefill
    signature to fall inside the engine's AOT bucket set: a shape
    outside the set would retrace per request mix at serving time
    (PTRC002-class), and the engine would raise on it."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.analysis import ProgramAnalyzer
    from paddle_tpu.analysis.core import Diagnostic, Report
    from paddle_tpu.models.gpt import (GPTForPretraining, GPTModel,
                                       gpt_tiny_config)
    from paddle_tpu.ops._dispatch import unwrap
    from paddle_tpu.serving import ServingEngine, simulate_decode_signatures
    from paddle_tpu.serving.engine import chunk_prefill_fn, decode_step_fn
    import functools

    paddle.seed(0)
    cfg = gpt_tiny_config()
    model = GPTForPretraining(GPTModel(cfg))
    # aot=False: the lint is abstract — no bucket programs compile here
    eng = ServingEngine(model, page_size=8, decode_buckets=(1, 2, 4),
                        aot=False)
    pool = eng.pool
    bucket = eng.decode_buckets[-1]
    # lint the program the engine actually compiles: the engines wrap
    # their step fns in the auto-fusion rewrite before jit, so the lint
    # targets do too (a no-op when nothing matches or the env gate is
    # off)
    from paddle_tpu.analysis import rewrite
    _fuse = (rewrite.autofuse if rewrite.autofuse_enabled()
             else (lambda f, label=None: f))
    fn = _fuse(functools.partial(decode_step_fn,
                                 eps=cfg.layer_norm_epsilon,
                                 temperature=0.0, top_k=0,
                                 use_kernel=False),
               label="serving.decode_step")

    def decode(kp, vp, tokens, positions, table, lens):
        # analyzer hands Tensor-wrapped tracers; the decode step is pure
        # jax — unwrap at the boundary (key=None: greedy)
        a = [unwrap(t) for t in (kp, vp, tokens, positions, table, lens)]
        return fn(eng.params, *a, None)

    i32 = jnp.int32
    kp = jax.ShapeDtypeStruct(pool.k_pages.shape, pool.k_pages.dtype)
    reports = [ProgramAnalyzer(
        world_size=world_size, hbm_budget_gb=hbm_budget_gb).analyze(
        decode, kp, kp,
        jax.ShapeDtypeStruct((bucket,), i32),
        jax.ShapeDtypeStruct((bucket,), i32),
        jax.ShapeDtypeStruct((bucket, pool.max_pages_per_seq), i32),
        jax.ShapeDtypeStruct((bucket,), i32),
        name="serving.decode_step")]

    diags = []
    # the closure proof runs once per ENGINE MODE — the classic
    # bucketed engine, the chunked/prefix-cache engine (whose prefill
    # side is ONE traced-offset chunk program), the disaggregated
    # engine (per-bucket prefill programs on the prefill mesh + scatter
    # landings on the decode mesh), and the MoE engine (ERNIE-MoE
    # dense/MoE stack, fused Pallas dispatch — classic prefill
    # semantics, its own bucket/pool sizing). Each mode's allowed set
    # must match what the real engine would AOT-compile, and every
    # signature the real scheduler requests must fall inside it.
    from paddle_tpu.models import (ErnieMoeForPretraining, ErnieMoeModel,
                                   ernie_moe_tiny_config)
    from paddle_tpu.serving import MoEServingEngine
    mcfg = ernie_moe_tiny_config(num_hidden_layers=2, hidden_size=32,
                                 num_attention_heads=2,
                                 intermediate_size=64, num_experts=4,
                                 max_position_embeddings=64)
    mmodel = ErnieMoeForPretraining(ErnieMoeModel(mcfg))
    mmodel.eval()
    moe_eng = MoEServingEngine(mmodel, page_size=8,
                               decode_buckets=(1, 2, 4), aot=False)
    chunk = eng.prefill_buckets[0]
    modes = {
        "classic": (dict(), eng),
        "chunked": (dict(prefill_chunk=chunk),
                    ServingEngine(model, page_size=8,
                                  decode_buckets=(1, 2, 4),
                                  prefill_chunk=chunk, aot=False)),
        "disagg": (dict(disaggregated=True),
                   ServingEngine(model, page_size=8,
                                 decode_buckets=(1, 2, 4),
                                 disaggregated=True, aot=False)),
        # MoE: classic prefill/decode semantics over the MoE engine's
        # own pool/bucket config — proves the scheduler can never ask
        # the MoE decode program for an uncompiled shape either
        "moe": (dict(), moe_eng),
    }
    for mode, (sim_kw, mode_eng) in modes.items():
        used_d, used_p, ok_d, ok_p = simulate_decode_signatures(
            mode_eng.decode_buckets, mode_eng.prefill_buckets,
            mode_eng.pool.page_size, mode_eng.pool.num_pages,
            mode_eng.max_seq_len, n_requests=200, seed=0, **sim_kw)
        if ok_d != mode_eng.decode_signatures():
            # the closure proof is only a proof if the probe's allowed
            # set IS the set the real engine AOT-compiles
            diags.append(Diagnostic(
                "PTRC002", "recompile", "error",
                f"[{mode}] shape-probe allowed set {sorted(ok_d)} "
                f"drifted from the engine's AOT decode signatures "
                f"{sorted(mode_eng.decode_signatures())}",
                op="serving.decode"))
        if ok_p != mode_eng.prefill_signatures():
            diags.append(Diagnostic(
                "PTRC002", "recompile", "error",
                f"[{mode}] shape-probe allowed prefill set "
                f"{sorted(ok_p, key=str)} drifted from the engine's "
                f"AOT prefill signatures "
                f"{sorted(mode_eng.prefill_signatures(), key=str)}",
                op="serving.prefill"))
        for used, ok, what in ((used_d, ok_d, "decode"),
                               (used_p, ok_p, "prefill")):
            escaped = sorted(used - ok, key=str)
            if escaped:
                diags.append(Diagnostic(
                    "PTRC002", "recompile", "error",
                    f"[{mode}] serving {what} requested shape(s) "
                    f"{escaped} outside the AOT bucket set "
                    f"{sorted(ok, key=str)} — every such shape "
                    f"retraces at serving time; widen the bucket "
                    f"config", op=f"serving.{what}"))
        # cancellation mix: the same replay with randomized mid-decode
        # deadline cancellations through the real scheduler's cancel()
        # path. Cancel is an EVICTION — it must introduce ZERO program
        # signatures outside the AOT set (never a recompile), and the
        # probe's allowed set must not move
        cd, cp, okd_c, okp_c = simulate_decode_signatures(
            mode_eng.decode_buckets, mode_eng.prefill_buckets,
            mode_eng.pool.page_size, mode_eng.pool.num_pages,
            mode_eng.max_seq_len, n_requests=200, seed=0,
            cancel_p=0.15, **sim_kw)
        if (okd_c, okp_c) != (ok_d, ok_p):
            diags.append(Diagnostic(
                "PTRC002", "recompile", "error",
                f"[{mode}+cancel] probe allowed set changed under the "
                f"cancellation mix — the cancel path must not alter "
                f"what the engine compiles", op="serving.cancel"))
        for used, ok, what in ((cd, ok_d, "decode"),
                               (cp, ok_p, "prefill")):
            escaped = sorted(used - ok, key=str)
            if escaped:
                diags.append(Diagnostic(
                    "PTRC002", "recompile", "error",
                    f"[{mode}+cancel] mid-decode cancellations drove "
                    f"{what} shape(s) {escaped} outside the AOT bucket "
                    f"set {sorted(ok, key=str)} — cancel must be an "
                    f"eviction, never a recompile",
                    op=f"serving.{what}"))
    rep = Report("serving.decode_buckets", diags)
    rep.emit()
    reports.append(rep)

    # the chunk program itself through the pass suite (abstract): it is
    # the only NEW serving-side program shape this engine family runs
    ceng = modes["chunked"][1]
    cpool = ceng.pool
    cfn = _fuse(functools.partial(chunk_prefill_fn,
                                  eps=cfg.layer_norm_epsilon,
                                  temperature=0.0, top_k=0),
                label="serving.chunk_prefill")

    def chunk_step(kp, vp, ids, off, clen, table, rows):
        a = [unwrap(t) for t in (kp, vp, ids, off, clen, table, rows)]
        return cfn(ceng.params, *a, None)

    ckp = jax.ShapeDtypeStruct(cpool.k_pages.shape, cpool.k_pages.dtype)
    C = ceng.prefill_chunk
    reports.append(ProgramAnalyzer(
        world_size=world_size, hbm_budget_gb=hbm_budget_gb).analyze(
        chunk_step, ckp, ckp,
        jax.ShapeDtypeStruct((1, C), i32),
        jax.ShapeDtypeStruct((), i32),
        jax.ShapeDtypeStruct((), i32),
        jax.ShapeDtypeStruct((1, cpool.max_pages_per_seq), i32),
        jax.ShapeDtypeStruct((C,), i32),
        name="serving.chunk_prefill"))

    # the MoE decode program (fused Pallas dispatch inside) through the
    # full pass suite: the fused path must lint clean — in particular
    # the cost pass's PTCS004 fusion-opportunity diagnostic must NOT
    # fire on it (a pallas_call IS the fused form)
    from paddle_tpu.serving.moe_engine import moe_decode_step_fn
    mpool = moe_eng.pool
    mbucket = moe_eng.decode_buckets[-1]
    mfn = _fuse(functools.partial(
        moe_decode_step_fn, kinds=moe_eng.kinds,
        eps=mcfg.layer_norm_eps, top_k=mcfg.top_k, temperature=0.0,
        topk_sample=0, use_kernel=False, use_fused_moe=True),
        label="serving.moe_decode_step")

    def moe_decode(kp, vp, tokens, positions, table, lens):
        a = [unwrap(t) for t in (kp, vp, tokens, positions, table, lens)]
        return mfn(moe_eng.params, *a, None)

    mkp = jax.ShapeDtypeStruct(mpool.k_pages.shape, mpool.k_pages.dtype)
    reports.append(ProgramAnalyzer(
        world_size=world_size, hbm_budget_gb=hbm_budget_gb).analyze(
        moe_decode, mkp, mkp,
        jax.ShapeDtypeStruct((mbucket,), i32),
        jax.ShapeDtypeStruct((mbucket,), i32),
        jax.ShapeDtypeStruct((mbucket, mpool.max_pages_per_seq), i32),
        jax.ShapeDtypeStruct((mbucket,), i32),
        name="serving.moe_decode_step"))
    return reports


def lint_collectives(world_size=None, hbm_budget_gb=None):
    """Compressed-collective gate, seeded both ways:

    (1) a schedule where ranks differ ONLY in wire compression
    (rank 0 int8-compressed all_reduce/reduce_scatter + in-jit ``_q``
    prims, rank 1 uncompressed) must lint CLEAN — the PTCC passes key
    collectives on (op, group, dtype, shape) with wire dtype as
    metadata, so compression never reads as schedule divergence
    (false deadlock);

    (2) a schedule with a GENUINE divergence hidden behind a compressed
    op (rank 0 compressed all_reduce, rank 1 barrier) must still raise
    PTCC001 — compression must not mask real deadlocks. The gate FAILS
    if either direction misbehaves."""
    import jax
    import jax.numpy as jnp
    import paddle_tpu.distributed as dist
    from paddle_tpu.analysis import ProgramAnalyzer
    from paddle_tpu.analysis.core import Diagnostic, Report

    ws = world_size or 2
    SDS = jax.ShapeDtypeStruct

    def mixed_compression(x):
        if dist.get_rank() == 0:
            dist.all_reduce(x, compress="int8")
            dist.reduce_scatter(x, None, compress="int8")
            dist.prims.c_allreduce_sum_q(x, "dp", wire="int8")
        else:
            dist.all_reduce(x)
            dist.reduce_scatter(x, None)
            dist.prims.c_allreduce_sum(x, "dp")
        return x

    reports = [ProgramAnalyzer(
        world_size=ws, hbm_budget_gb=hbm_budget_gb).analyze(
        mixed_compression, SDS((8, 4), jnp.float32),
        name="collectives.mixed_compression")]

    def seeded_divergence(x):
        if dist.get_rank() == 0:
            dist.all_reduce(x, compress="int8")
        else:
            dist.barrier()
        return x

    probe = ProgramAnalyzer(world_size=ws).analyze(
        seeded_divergence, SDS((8, 4), jnp.float32),
        name="collectives.seeded_divergence", emit=False)
    diags = []
    if not any(d.code in ("PTCC001", "PTCC002")
               for d in probe.diagnostics):
        diags.append(Diagnostic(
            "PTCC001", "collective", "error",
            "seeded compressed-vs-barrier divergence was NOT flagged — "
            "the compressed-collective lint lost the deadlock signal "
            "(wire compression must be metadata, not identity)",
            op="all_reduce"))
    rep = Report("collectives.divergence_still_caught", diags)
    rep.emit()
    reports.append(rep)
    return reports


def lint_capture(world_size=None, hbm_budget_gb=None):
    """Whole-program capture gate (dy2static ``convert_call``): every
    zoo model is captured via ``to_static`` with GENUINELY NESTED
    helpers carrying tensor-dependent control flow. Three assertions
    per model, each a Report the gate fails on:

    1. **parity** — dygraph loss == to_static loss (the captured
       program computes the same numbers, nested helpers included);
    2. **capture** — the nested helpers' code objects landed in the
       conversion cache (a helper that silently escaped capture would
       still pass parity eagerly — this catches it);
    3. **lint** — the captured StaticFunction runs the full pass suite
       clean (hostsync/recompile/collective/amp over the WHOLE
       program, transitively-converted callees attributed to their
       original source).

    Unlike the other lint targets this executes the tiny models for
    real (the AST fallback converts lazily at trace time) — still
    seconds at zoo-tiny configs on CPU."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    import paddle_tpu as paddle
    from paddle_tpu.analysis import ProgramAnalyzer
    from paddle_tpu.analysis.core import Diagnostic, Report
    from paddle_tpu.jit import dy2static as d2s
    from paddle_tpu.models.bert import (BertForPretraining, BertModel,
                                        bert_tiny_config, _mlm_head_loss,
                                        additive_attention_mask)
    from paddle_tpu.models.ernie import (_ernie_mlm_head_loss,
                                         _guard_nonfinite)
    from paddle_tpu.models.gpt import (GPTForPretraining, GPTModel,
                                       GPTPretrainingCriterion,
                                       damp_loss_spike, gpt_tiny_config)
    from paddle_tpu.models import (ErnieMoeForPretraining, ErnieMoeModel,
                                   ernie_moe_tiny_config)

    B, S = 2, 16
    reports = []

    def gate(name, entry, helpers, vocab):
        rng = np.random.default_rng(0)
        ids = paddle.to_tensor(
            rng.integers(0, vocab, (B, S)).astype(np.int64))
        labels = paddle.to_tensor(
            rng.integers(0, vocab, (B, S)).astype(np.int64))
        diags = []
        want = float(np.asarray(entry(ids, labels).numpy()))
        sf = paddle.jit.to_static(entry)
        got = float(np.asarray(sf(ids, labels).numpy()))
        if not np.isfinite(got) or not np.allclose(got, want, rtol=1e-4,
                                                   atol=1e-5):
            diags.append(Diagnostic(
                "PTCP001", "capture", "error",
                f"dygraph vs to_static loss parity broke under "
                f"whole-program capture: eager {want!r} vs captured "
                f"{got!r}", op=name))
        converted = d2s.converted_code_objects()
        for h in helpers:
            if h.__code__ not in converted:
                diags.append(Diagnostic(
                    "PTCP002", "capture", "error",
                    f"nested helper {h.__name__!r} escaped whole-program "
                    f"capture — convert_call never converted it; the "
                    f"compiled program silently runs un-rewritten "
                    f"control flow", op=name))
        rep = Report(f"{name}.capture", diags)
        rep.emit()
        reports.append(rep)
        i64 = jax.ShapeDtypeStruct((B, S), jnp.int64)
        reports.append(ProgramAnalyzer(
            world_size=world_size, hbm_budget_gb=hbm_budget_gb).analyze(
            sf, i64, i64, name=f"{name}.captured_program"))

    paddle.seed(0)
    gcfg = gpt_tiny_config()
    gmodel = GPTForPretraining(GPTModel(gcfg))
    gmodel.eval()
    crit = GPTPretrainingCriterion()

    def gpt_entry(ids, labels):
        # threshold=0 forces the damped branch (tiny-config loss ~ln V)
        return damp_loss_spike(crit(gmodel(ids), labels), threshold=0.0)

    gate("gpt.capture_nested", gpt_entry, [damp_loss_spike],
         gcfg.vocab_size)

    paddle.seed(0)
    bmodel = BertForPretraining(BertModel(bert_tiny_config()))
    bmodel.eval()

    def bert_entry(ids, labels):
        return bmodel.forward_with_mlm_loss(ids, labels,
                                            loss_spike_damping=True)

    gate("bert.capture_nested", bert_entry,
         [BertForPretraining.forward_with_mlm_loss, _mlm_head_loss,
          additive_attention_mask, damp_loss_spike],
         bmodel.bert.config.vocab_size)

    paddle.seed(0)
    mcfg = ernie_moe_tiny_config(num_hidden_layers=2)
    mmodel = ErnieMoeForPretraining(ErnieMoeModel(mcfg))
    mmodel.eval()

    def ernie_entry(ids, labels):
        return mmodel.forward_with_mlm_loss(ids, labels,
                                            nonfinite_guard=True)

    gate("ernie_moe.capture_nested", ernie_entry,
         [ErnieMoeForPretraining.forward_with_mlm_loss,
          _ernie_mlm_head_loss, _guard_nonfinite],
         mcfg.vocab_size)
    return reports


def lint_fusion(world_size=None, hbm_budget_gb=None):
    """Auto-fusion gate, seeded both ways. A deliberately glue-heavy
    unfused MoE gate+dispatch program (sizes over the PTCS004 floor) is
    traced through the analyzer:

    - rewrite ON (default): the auto-fusion pass must land — the lint
      sees the REWRITTEN program, so PTCS004 must drop to zero and
      PTCS005 must report the fused site (unless the site is explicitly
      suppressed via PADDLE_AUTOFUSE_SUPPRESS);
    - rewrite OFF (``--no-autofuse`` / PADDLE_NO_AUTOFUSE=1): the
      pre-rewrite program must still carry >= 1 PTCS004 — the inventory
      the rewrite consumes; losing it silently would blind the pass.
    """
    import jax
    import jax.numpy as jnp
    from paddle_tpu.analysis import ProgramAnalyzer
    from paddle_tpu.analysis import rewrite
    from paddle_tpu.analysis.core import Diagnostic, Report
    from paddle_tpu.kernels.moe_dispatch import (reference_moe_combine,
                                                 reference_moe_dispatch)
    from paddle_tpu.ops._dispatch import unwrap

    S, M, E, K = 4096, 512, 16, 2
    C = int(1.2 * K * S / E)

    def moe_glue(x, gw, gb, eo):
        ei, comb, val, _, _ = reference_moe_dispatch(
            x, gw, gb, num_expert=E, capacity=C, top_k=K,
            gate_kind="renorm")
        return ei, reference_moe_combine(eo, val, comb)

    fused = rewrite.autofuse(moe_glue, label="fusion.moe_glue")

    def entry(x, gw, gb, eo):
        return fused(*(unwrap(t) for t in (x, gw, gb, eo)))

    SDS = jax.ShapeDtypeStruct
    rep = ProgramAnalyzer(
        world_size=world_size, hbm_budget_gb=hbm_budget_gb).analyze(
        entry, SDS((S, M), jnp.float32), SDS((M, E), jnp.float32),
        SDS((E,), jnp.float32), SDS((E * C, M), jnp.float32),
        name="fusion.moe_glue")
    reports = [rep]
    n004 = sum(1 for d in rep.diagnostics if d.code == "PTCS004")
    n005 = sum(1 for d in rep.diagnostics if d.code == "PTCS005")
    diags = []
    if rewrite.autofuse_enabled():
        suppressed = bool(rewrite.suppressed_sites())
        if n004 and not suppressed:
            diags.append(Diagnostic(
                "PTCS004", "cost", "error",
                f"auto-fusion is ON but the glue-heavy MoE probe still "
                f"lints {n004} PTCS004 fusion opportunit"
                f"{'y' if n004 == 1 else 'ies'} — the rewrite pass "
                f"failed to consume its own inventory (match regression "
                f"or parity reject)", op="fusion.moe_glue"))
        if not n005 and not suppressed:
            diags.append(Diagnostic(
                "PTCS005", "cost", "error",
                "auto-fusion is ON but the rewritten MoE probe carries "
                "no PTCS005 annotation — either the rewrite did not "
                "fire or the cost pass lost the fused-kernel join",
                op="fusion.moe_glue"))
    elif not n004:
        diags.append(Diagnostic(
            "PTCS004", "cost", "error",
            "auto-fusion is OFF (--no-autofuse) but the pre-rewrite "
            "glue-heavy MoE probe lints no PTCS004 — the fusion-"
            "opportunity inventory the rewrite consumes went silent",
            op="fusion.moe_glue"))
    gate = Report("fusion.autofuse_gate", diags)
    gate.emit()
    reports.append(gate)
    return reports


MODELS = {"gpt": lint_gpt, "bert": lint_bert, "ernie_moe": lint_ernie_moe,
          "serving": lint_serving, "collectives": lint_collectives,
          "capture": lint_capture, "fusion": lint_fusion}


def lint_model(name, world_size=None, hbm_budget_gb=None):
    """Lint one built-in model; returns [Report, ...] (eager + static)."""
    return MODELS[name](world_size=world_size, hbm_budget_gb=hbm_budget_gb)


# ---------------------------------------------------------------------------


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="static lint over models / train steps / programs")
    ap.add_argument("--model", default="all",
                    choices=["all"] + sorted(MODELS))
    ap.add_argument("--world-size", type=int, default=None,
                    help="simulated ranks for the collective pass "
                         "(default: env world size, min 2)")
    ap.add_argument("--hbm-budget-gb", type=float, default=16.0,
                    help="per-chip HBM budget for the PTMM001 "
                         "OOM-before-compile gate (default 16, the chip; "
                         "0 disables)")
    ap.add_argument("--json", action="store_true",
                    help="one JSON line per report")
    ap.add_argument("--errors-only", action="store_true",
                    help="exit 0 despite warnings (default: any "
                         "non-clean report fails, matching Report.clean)")
    ap.add_argument("--no-autofuse", action="store_true",
                    help="lint the PRE-rewrite programs (sets "
                         "PADDLE_NO_AUTOFUSE=1): PTCS004 fusion "
                         "opportunities stay visible instead of being "
                         "consumed by the analysis.rewrite pass")
    args = ap.parse_args(argv)
    _force_platform()
    if args.no_autofuse:
        os.environ["PADDLE_NO_AUTOFUSE"] = "1"

    names = sorted(MODELS) if args.model == "all" else [args.model]
    reports = []
    for n in names:
        reports.extend(lint_model(n, world_size=args.world_size,
                                  hbm_budget_gb=args.hbm_budget_gb or None))

    failed = False
    # with the rewrite on, the zoo's whole PTCS004 inventory must be
    # consumed (each chain either rewritten — flipping to PTCS005 — or
    # explicitly suppressed); any survivor is a gate failure even
    # though PTCS004 itself is only an info
    from paddle_tpu.analysis import rewrite as _rewrite
    if _rewrite.autofuse_enabled():
        leftovers = []
        for rep in reports:
            for d in rep.diagnostics:
                if d.code != "PTCS004" or d.severity == "error":
                    continue
                site = str((getattr(d, "extra", None) or {})
                           .get("fusion", {}).get("site", ""))
                if not _rewrite._is_suppressed(site):
                    leftovers.append((rep.target_name, site))
        if leftovers:
            failed = True
            print(f"FUSION GATE: {len(leftovers)} PTCS004 chain(s) "
                  f"survived the auto-fusion rewrite: {leftovers}",
                  flush=True)
    for rep in reports:
        # a failed trace checked nothing — always a gate failure, even
        # under --errors-only
        bad = bool(rep.errors or rep.trace_error) if args.errors_only \
            else not rep.clean
        failed = failed or bad
        if args.json:
            print(json.dumps({
                "target": rep.target_name,
                "clean": rep.clean,
                "errors": len(rep.errors),
                "warnings": len(rep.warnings),
                "infos": len(rep.infos),
                "trace_error": rep.trace_error,
                "diagnostics": [
                    {"code": d.code, "pass": d.pass_name,
                     "severity": d.severity, "op": d.op, "file": d.file,
                     "line": d.line, "message": d.message}
                    for d in rep.diagnostics],
            }), flush=True)
        else:
            print(rep, flush=True)
    return 1 if failed else 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except Exception:  # harness crash ≠ lint failure
        import traceback
        traceback.print_exc()
        sys.exit(2)
