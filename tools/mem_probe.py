"""Pipeline-memory probe: reproducible ``memory_analysis()`` sweeps.

VERDICT r4 #3/#4: the README's "XLA temp memory 4x below GPipe at
n_micro=32" claim previously lived only in a commit message; this tool
makes it (and the 13B fits-or-not question) a checked-in, re-runnable
artifact. It AOT-lowers the ``GPTHybridTrainStep`` via
``GPTHybridTrainStep.abstract`` + ``lower_step`` — no parameter buffers
are materialized, so 13B-scale programs compile on a laptop-sized host —
and prints one JSON line per (schedule, n_micro, remat) combo with XLA's
per-device memory breakdown.

The probe runs on a VIRTUAL CPU mesh: it re-execs itself with
``JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=N``
when the current backend doesn't provide enough devices, so
``python tools/mem_probe.py --config tiny`` works from any environment.

Examples:
  python tools/mem_probe.py                         # tiny sweep (CI-fast)
  python tools/mem_probe.py --config 13b --mp 4 --pp 4 --batch 16 \
      --seq 2048 --n-micro 16 --schedules 1f1b      # the north-star probe

Parity: the memory rationale of reference ``pipeline_parallel.py:119``
(1F1B bounds live micro-batches) + ``fleet/recompute`` (remat), measured
instead of asserted.
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _mesh_devices_needed(args):
    return args.dp * args.mp * args.pp * args.sharding


def _maybe_respawn(args):
    """Re-exec on a virtual CPU mesh. The parent NEVER touches jax: the
    default backend is the real TPU (which probing must not hold, and
    whose tunnel can hang first contact), and the device count must be
    forced via XLA_FLAGS before the backend exists. The child re-forces
    CPU through jax.config in main() — the axon sitecustomize ignores
    the JAX_PLATFORMS env var."""
    if os.environ.get("_MEM_PROBE_RESPAWNED"):
        return None
    need = _mesh_devices_needed(args)
    env = dict(os.environ)
    env.update({
        "_MEM_PROBE_RESPAWNED": "1",
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": (env.get("XLA_FLAGS", "") +
                      f" --xla_force_host_platform_device_count={need}")
        .strip(),
    })
    return subprocess.run([sys.executable, os.path.abspath(__file__)]
                          + sys.argv[1:], env=env).returncode


def probe_one(cfg, hcg, schedule, n_micro, remat, vpp, batch, seq,
              compute_dtype="bfloat16", param_dtype=None,
              moment_dtype=None, compare_static=False):
    from paddle_tpu.models.gpt import GPTHybridTrainStep

    step = GPTHybridTrainStep.abstract(
        cfg, hcg, n_micro=n_micro, remat=remat,
        pipeline_schedule="1f1b" if schedule in ("1f1b", "interleaved")
        else "gpipe",
        virtual_pp_degree=vpp if schedule == "interleaved" else 1,
        compute_dtype=compute_dtype, param_dtype=param_dtype,
        moment_dtype=moment_dtype)
    compiled = step.lower_step(batch, seq).compile()
    ma = compiled.memory_analysis()
    gb = 1024 ** 3
    rec = {
        "schedule": schedule, "n_micro": n_micro,
        "remat": remat if isinstance(remat, str) else bool(remat),
        "vpp": vpp if schedule == "interleaved" else 1,
        "temp_gb": round(ma.temp_size_in_bytes / gb, 4),
        "argument_gb": round(ma.argument_size_in_bytes / gb, 4),
        "output_gb": round(ma.output_size_in_bytes / gb, 4),
        # donation makes params/opt-state alias in+out, so live HBM is
        # args (params+state+data) + temps, NOT args+outputs+temps
        "peak_hbm_gb": round((ma.argument_size_in_bytes
                              + ma.temp_size_in_bytes) / gb, 4),
    }
    if compare_static:
        # predicted-vs-XLA cross-check: the liveness estimator walks the
        # SAME step's jaxpr (trace only, no second compile) and the
        # relative error column keeps it honest in CI
        from paddle_tpu.analysis.predict import predict_hybrid_step
        pred = predict_hybrid_step(step, batch, seq)
        p = pred["memory"].peak_bytes
        x = ma.argument_size_in_bytes + ma.temp_size_in_bytes
        rec["predicted_peak_gb"] = round(p / gb, 4)
        rec["predicted_temp_gb"] = round(
            pred["memory"].temp_peak_bytes / gb, 4)
        rec["rel_err"] = round((p - x) / x, 4) if x else 0.0
    return rec


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", default="tiny",
                    choices=["tiny", "345m", "1.3b", "13b"])
    ap.add_argument("--dp", type=int, default=1)
    ap.add_argument("--mp", type=int, default=1)
    ap.add_argument("--pp", type=int, default=4)
    ap.add_argument("--sharding", type=int, default=1)
    ap.add_argument("--batch", type=int, default=0)
    ap.add_argument("--seq", type=int, default=0)
    ap.add_argument("--n-micro", type=int, nargs="*", default=None)
    ap.add_argument("--schedules", nargs="*",
                    default=["gpipe", "1f1b", "interleaved"])
    ap.add_argument("--remat", nargs="*", default=["none", "full", "dots"])
    ap.add_argument("--vpp", type=int, default=2)
    ap.add_argument("--param-dtype", default=None)
    ap.add_argument("--moment-dtype", default=None)
    ap.add_argument("--compute-dtype", default="bfloat16",
                    help="activation/compute dtype; use float32 for a "
                         "like-for-like --compare-static run (XLA's CPU "
                         "backend pads bf16 programs with f32 conversion "
                         "buffers a TPU never allocates)")
    ap.add_argument("--compare-static", action="store_true",
                    help="also run the static liveness peak-HBM "
                         "estimator (paddle_tpu.analysis) per combo and "
                         "print predicted_peak_gb + rel_err columns")
    args = ap.parse_args()

    rc = _maybe_respawn(args)
    if rc is not None:
        sys.exit(rc)

    import jax
    jax.config.update("jax_platforms", "cpu")  # axon ignores the env var

    from paddle_tpu.distributed import mesh as mesh_mod
    from paddle_tpu.distributed.mesh import HybridCommunicateGroup
    from paddle_tpu.models.gpt import (gpt_tiny_config, gpt_345m_config,
                                       gpt_1p3b_config, gpt_13b_config)

    cfgs = {"tiny": gpt_tiny_config, "345m": gpt_345m_config,
            "1.3b": gpt_1p3b_config, "13b": gpt_13b_config}
    if args.config == "tiny":
        # enough layers for every schedule in the sweep (interleaved
        # needs num_layers % (pp * vpp) == 0)
        cfg = gpt_tiny_config(num_layers=args.pp * max(args.vpp, 2))
    else:
        cfg = cfgs[args.config]()
    batch = args.batch or {"tiny": 8, "345m": 8, "1.3b": 8, "13b": 16}[
        args.config]
    seq = args.seq or min(512, cfg.max_position_embeddings)
    micros = args.n_micro or [args.pp, 4 * args.pp]
    remats = [{"none": False, "full": True, "dots": "dots"}[r]
              for r in args.remat]

    mesh_mod._global_mesh, mesh_mod._hcg = None, None
    hcg = HybridCommunicateGroup(dp_degree=args.dp, mp_degree=args.mp,
                                 pp_degree=args.pp,
                                 sharding_degree=args.sharding)
    meta = {"config": args.config, "hidden": cfg.hidden_size,
            "layers": cfg.num_layers, "batch": batch, "seq": seq,
            "mesh": {"dp": args.dp, "mp": args.mp, "pp": args.pp,
                     "sharding": args.sharding}}
    print(json.dumps({"probe": "mem", **meta}), flush=True)
    for schedule in args.schedules:
        for n_micro in micros:
            if batch % n_micro:
                continue
            for remat in remats:
                try:
                    rec = probe_one(cfg, hcg, schedule, n_micro, remat,
                                    args.vpp, batch, seq,
                                    compute_dtype=args.compute_dtype,
                                    param_dtype=args.param_dtype,
                                    moment_dtype=args.moment_dtype,
                                    compare_static=args.compare_static)
                except Exception as e:
                    rec = {"schedule": schedule, "n_micro": n_micro,
                           "remat": str(remat), "error": repr(e)[:200]}
                print(json.dumps(rec), flush=True)


if __name__ == "__main__":
    main()
