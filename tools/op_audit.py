#!/usr/bin/env python
"""Op-surface audit: diff the package's exported callables against the
reference's phi op registry (`phi/api/yaml/ops.yaml` + `legacy_ops.yaml`).

Usage::

    python tools/op_audit.py [--yaml-dir /root/reference/paddle/phi/api/yaml]

Prints per-yaml coverage and the missing-op list. Ops that are internal
machinery in the reference (optimizer update kernels, grad-only ops,
infrastructure like feed/fetch) are classified out separately so the gap
list is actionable. Exit code 0 always — this is an audit, not a gate;
the current expected-missing set is asserted by tests/test_op_audit.py
so regressions (an op disappearing) fail CI.
"""
from __future__ import annotations

import argparse
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# yaml op name -> public API name when they differ (kernel-level names
# vs the user surface the reference itself exposes them through)
RENAMES = {
    "memcpy_d2h": None, "memcpy_h2d": None, "fused_gemm_epilogue": None,
    "elementwise_pow": "pow",
    "multiclass_nms3": "multiclass_nms",
    "cross_entropy_with_softmax": "softmax_with_cross_entropy",
    "bce_loss": "binary_cross_entropy",
    "sigmoid_cross_entropy_with_logits": "binary_cross_entropy_with_logits",
    "kldiv_loss": "kl_div",
    "logsigmoid": "log_sigmoid",
    "tanh_shrink": "tanhshrink",
    "warpctc": "ctc_loss",
    "warprnnt": "rnnt_loss",
    "huber_loss": "huber_loss",
    # interpolation kernels -> one interpolate/upsample surface
    "bicubic_interp": "interpolate", "bilinear_interp": "interpolate",
    "linear_interp": "interpolate", "nearest_interp": "interpolate",
    "trilinear_interp": "interpolate",
    # pooling kernels -> functional pools
    "pool2d": "max_pool2d", "pool3d": "max_pool3d",
    "max_pool2d_with_index": "max_pool2d",
    "max_pool3d_with_index": "max_pool3d",
    "unpool": "max_unpool2d", "unpool3d": "max_unpool3d",
    # conv variants -> conv2d(groups=...)
    "depthwise_conv2d": "conv2d",
    "depthwise_conv2d_transpose": "conv2d_transpose",
    # fft kernels -> fft module surface
    "fft_c2c": "fft", "fft_r2c": "rfft", "fft_c2r": "irfft",
    # norms / reductions
    "frobenius_norm": "norm", "p_norm": "norm", "mean_all": "mean",
    "squared_l2_norm": None,  # grad-clip internal
    "matrix_rank_tol": "matrix_rank",
    "split_with_num": "split",
    "repeat_interleave_with_tensor_index": "repeat_interleave",
    "segment_pool": "segment_sum",
    # random kernels -> creation/init surface
    "gaussian": "randn", "truncated_gaussian_random": "TruncatedNormal",
    "uniform_inplace": "uniform_", "exponential_": "exponential_",
    "dirichlet": "Dirichlet",
    "full_batch_size_like": "full_like",
    "fill": "fill_",
    # layers as the surface
    "rnn": "RNN", "sync_batch_norm_": "SyncBatchNorm",
    "spectral_norm": "spectral_norm",
    "copy_to": "to",
    "merge_selected_rows": None, "npu_identity": None,
    "average_accumulates_": None,  # ModelAverage internal
    "decode_jpeg": "decode_jpeg",
    "deformable_conv": "deform_conv2d",
    "fill_diagonal": "fill_diagonal_",
    "pad3d": "pad",
}

# reference-internal ops that are not user API surface: optimizer update
# kernels (the optimizer classes ARE the surface here), grad-only and
# infrastructure ops, and ops subsumed by jax/XLA by design
INTERNAL = {
    # optimizer update kernels (surface = paddle_tpu.optimizer classes)
    "adadelta_", "adagrad_", "adam_", "adamax_", "adamw_", "lamb_",
    "momentum_", "sgd_", "rmsprop_", "ftrl", "dpsgd", "sparse_momentum",
    "merged_adam_", "merged_momentum_", "fused_adam_",
    # infrastructure / framework-internal
    "feed", "fetch", "assign_out_", "assign_pos", "assign_value_",
    "share_buffer", "share_data", "print", "load_combine", "save_combine",
    "memcpy", "memcpy_d2h", "memcpy_h2d", "get_tensor_from_selected_rows",
    "read_file", "recv_v2", "send_v2", "batch_fc", "c_broadcast",
    "c_concat", "c_identity", "c_sync_calc_stream", "c_sync_comm_stream",
    "c_allgather", "c_allreduce_max", "c_allreduce_min", "c_allreduce_prod",
    "c_allreduce_sum", "c_embedding", "c_softmax_with_cross_entropy",
    "c_split", "mp_allreduce_sum_", "all_reduce", "all_gather", "all_to_all",
    "broadcast", "reduce", "reduce_scatter", "p_recv", "p_send",
    "barrier", "global_gather", "global_scatter", "distributed_lookup_table",
    "distributed_push_sparse", "partial_allgather_", "partial_recv",
    "partial_send", "random_routing", "limit_by_capacity",
    "prune_gate_by_capacity", "number_count",
    # amp-internal
    "check_finite_and_unscale_", "update_loss_scaling_", "cast_label",
    # XLA-owned / runtime-owned
    "coalesce_tensor", "coalesce_tensor_", "run_program", "cudnn_lstm",
    "fusion_group", "share_var", "onednn_to_paddle_layout",
    "dequantize_linear", "quantize_linear",  # int8 deploy path (known gap)
    "straight_through_estimator", "fake_channel_wise_quantize_abs_max",
    # beam-search internals (greedy decode documented gap)
    "beam_search", "beam_search_decode",
    # data-structure ops for lod/selected-rows (no lod tensors by design)
    "lod_array_length", "array_length", "array_read", "array_write",
    "array_to_tensor", "create_array", "create_array_like",
    "tensor_array_to_tensor", "reset_lod",
    "sparse_coo_tensor", "sparse_csr_tensor",  # -> paddle_tpu.sparse
}


def yaml_ops(path):
    ops = []
    for line in open(path):
        m = re.match(r"- op\s*:\s*(\w+)", line)
        if m:
            ops.append(m.group(1))
    return ops


def collect_exports():
    """Every public callable reachable from the paddle_tpu surface."""
    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    linalg = paddle.linalg
    import paddle_tpu.fft as fft
    import paddle_tpu.signal as sig
    import paddle_tpu.sparse as sparse
    import paddle_tpu.geometric as geo
    import paddle_tpu.incubate as incubate
    import paddle_tpu.vision.ops as vops
    import paddle_tpu.distributed as dist
    import paddle_tpu.text as text
    import paddle_tpu.static.nn as snn
    import paddle_tpu.metric as metric
    import paddle_tpu.nn as nn
    import paddle_tpu.nn.initializer as init
    import paddle_tpu.nn.utils as nn_utils
    import paddle_tpu.distribution as distribution

    names = set()
    for mod in (paddle, F, linalg, fft, sig, sparse, geo, incubate, vops,
                dist, text, snn, metric, nn, init, nn_utils, distribution):
        for n in dir(mod):
            if not n.startswith("_"):
                names.add(n)
    # Tensor methods count (paddle.Tensor.xxx is API surface)
    for n in dir(paddle.Tensor):
        if not n.startswith("_"):
            names.add(n)
    from paddle_tpu.distributed.collective import prims
    for n in dir(prims):
        if not n.startswith("_"):
            names.add(n)
    return names


def audit(yaml_dir):
    exports = collect_exports()

    def present(op):
        if op in INTERNAL:
            return "internal"
        target = RENAMES.get(op, op)
        if target is None:
            return "internal"
        cands = {target, target.rstrip("_"), target + "_op"}
        base = target.rstrip("_")
        cands |= {base}
        # common yaml->api renames
        for pre in ("elementwise_", "reduce_"):
            if base.startswith(pre):
                cands.add(base[len(pre):])
        if any(c in exports for c in cands):
            return "yes"
        return "MISSING"

    results = {}
    for fname in ("ops.yaml", "legacy_ops.yaml"):
        ops = yaml_ops(os.path.join(yaml_dir, fname))
        rows = [(op, present(op)) for op in ops]
        results[fname] = rows
    return results


def fusion_audit(timeout_s=600):
    """``--fusion`` mode: every auto-fusion site in the zoo probe
    programs (the tiny serving engines' traced programs, GPT int8 +
    ERNIE-MoE) with its match status — fired / suppressed /
    parity_failed / unmatched / error — and the predicted Δstep-ms per
    fired rewrite. Sites come from ``analysis.rewrite``'s match
    records: fired rows are PTCS005 rewrites, unmatched rows are the
    PTCS004 chains no rule covers yet. Runs the probe in a CPU
    subprocess (same respawn contract as ``serving.predict``); honors
    ``PADDLE_NO_AUTOFUSE`` / ``PADDLE_AUTOFUSE_SUPPRESS`` so the
    suppressed states are auditable too. Exit 0 always — an audit,
    not a gate."""
    import json
    import subprocess
    import tempfile

    path = os.path.join(tempfile.mkdtemp(prefix="op_audit_fusion_"),
                        "autofusion.json")
    r = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.serving.predict",
         "--mode", "autofusion", "--export-records", path],
        capture_output=True, text=True, timeout=timeout_s,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    if not os.path.exists(path):
        print(f"fusion audit: probe failed (rc={r.returncode}): "
              f"{r.stderr[-300:]}")
        return []
    with open(path) as f:
        recs = json.load(f).get("records", [])
    by_status = {}
    print(f"{'status':<14} {'rule':<22} {'delta_ms':>10}  site (program)")
    for rec in recs:
        st = str(rec.get("status", "?"))
        by_status[st] = by_status.get(st, 0) + 1
        d = rec.get("predicted_delta_ms")
        delta = f"{d:+.6f}" if isinstance(d, (int, float)) else "-"
        print(f"{st:<14} {str(rec.get('rule') or '-'):<22} {delta:>10}  "
              f"{rec.get('site')} ({rec.get('label')})")
    print("totals: " + (", ".join(
        f"{k}={v}" for k, v in sorted(by_status.items())) or "no sites"))
    return recs


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--yaml-dir",
                    default="/root/reference/paddle/phi/api/yaml")
    ap.add_argument("--fusion", action="store_true",
                    help="audit auto-fusion sites (PTCS004/PTCS005) in "
                         "the zoo probe programs instead of op coverage")
    args = ap.parse_args()
    if args.fusion:
        fusion_audit()
        return []
    results = audit(args.yaml_dir)
    all_missing = []
    for fname, rows in results.items():
        missing = [op for op, st in rows if st == "MISSING"]
        internal = [op for op, st in rows if st == "internal"]
        n = len(rows)
        print(f"{fname}: {n} ops, {n - len(missing) - len(internal)} "
              f"covered, {len(internal)} internal-by-design, "
              f"{len(missing)} missing")
        all_missing += missing
    if all_missing:
        print("missing:", ", ".join(sorted(set(all_missing))))
    return sorted(set(all_missing))


if __name__ == "__main__":
    main()
