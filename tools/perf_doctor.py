"""Perf doctor CLI — "why is this run slow?" over a telemetry run dir.

Merges the run dir (per-rank JSONL → ``run_summary.json``, straggler
pass included), reconciles the measured step time against the static
cost model's ``*_predicted`` row, and prints the ranked report: gap
attribution across compute/HBM/comm/compile/skips, the named straggler
rank, anomaly tallies, crash exit codes, and any flight-recorder dumps
the run left behind.

Serving run dirs (those carrying a ``requests.jsonl`` stream) get a
serving section on top: per-request queue-wait/TTFT/per-token
percentiles, SLO violation + goodput findings, and — against a
``serving_predicted`` row (``python -m paddle_tpu.serving.predict``,
auto-discovered from ``<run_dir>/serving_predicted.json`` or the shared
``predicted.json``) — a measured-vs-predicted **per-output-token**
attribution whose queue/prefill/compile/decode buckets sum exactly to
the delta.

Usage::

    python tools/perf_doctor.py <run_dir>
    python tools/perf_doctor.py <run_dir> --predicted predicted.json
    python tools/perf_doctor.py <run_dir> --ops            # op-deviation table
    python tools/perf_doctor.py <run_dir> --json           # machine-readable
    python tools/perf_doctor.py <run_dir> --strict         # rc=1 on crit

``--ops`` appends the op-level attribution view when the run dir (or
``--predicted`` source) carries an ``attribution.json``
(:mod:`paddle_tpu.observability.opprof` output): the top-N sites by
|measured − predicted| deviation, the per-family rollup feeding the
PTCM001 drift finding, the exact sum-to-total line, and PTCS004 fusion
candidates with their MEASURED glue cost.

The predicted row is auto-discovered from ``<run_dir>/predicted.json``
(drop the output of ``python -m paddle_tpu.analysis.predict`` there);
without one the doctor still merges, names stragglers, and ranks
findings — only the roofline attribution is skipped.
"""
import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="predicted-vs-measured run diagnosis over a telemetry "
                    "run directory")
    ap.add_argument("run_dir", help="directory with events.rank*.jsonl / "
                                    "metrics.rank*.jsonl")
    ap.add_argument("--predicted", default=None,
                    help="JSON file with a *_predicted row (default: "
                         "<run_dir>/predicted.json when present)")
    ap.add_argument("--chip", default=None,
                    help="chip kind for comm-bandwidth math when the "
                         "predicted row names none (default v5e)")
    ap.add_argument("--straggler-threshold", type=float, default=1.3,
                    help="min slow-rank/median skew to name a straggler")
    ap.add_argument("--ops", action="store_true",
                    help="append the op-attribution deviation table "
                         "(needs <run_dir>/attribution.json)")
    ap.add_argument("--ops-top", type=int, default=10,
                    help="rows in the --ops deviation table")
    ap.add_argument("--json", action="store_true",
                    help="print the full report as JSON")
    ap.add_argument("--no-write", action="store_true",
                    help="do not (re)write run_summary.json")
    ap.add_argument("--strict", action="store_true",
                    help="exit 1 when any critical finding exists")
    args = ap.parse_args(argv)

    if not os.path.isdir(args.run_dir):
        print(f"perf_doctor: not a directory: {args.run_dir}",
              file=sys.stderr)
        return 2

    from paddle_tpu.observability.doctor import (diagnose_run_dir,
                                                 format_report,
                                                 load_predicted)
    if args.predicted is not None and load_predicted(args.predicted) is None:
        print(f"perf_doctor: no *_predicted row loadable from "
              f"{args.predicted}; falling back to <run_dir>/predicted.json "
              f"if present", file=sys.stderr)
    report = diagnose_run_dir(
        args.run_dir, predicted=args.predicted, chip=args.chip,
        write_summary=not args.no_write,
        straggler_threshold=args.straggler_threshold)
    if args.json:
        print(json.dumps(report, indent=2, sort_keys=True))
    else:
        print(format_report(report,
                            ops_top=args.ops_top if args.ops else None))
    if args.ops and report.get("op_attribution") is None:
        print("perf_doctor: --ops requested but no attribution.json in "
              "the run dir (generate one with "
              "paddle_tpu.observability.opprof)", file=sys.stderr)
    if args.strict and any(f["severity"] == "crit"
                           for f in report["findings"]):
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
