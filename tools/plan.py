#!/usr/bin/env python
"""Cost-model parallelism planner CLI.

Ranks (dp, mp, pp, sharding, n_micro, remat, donation, wire dtype)
plans for a GPT-family model on N chips, scored by tracing the REAL
hybrid train step on a virtual mesh through the static cost/memory
model — no devices, no compile, a 13B/64-chip plan in seconds::

    python tools/plan.py --model gpt_13b --devices 64 --chip v5e
    python tools/plan.py --model gpt_13b --devices 16 --json   # bench row
    python tools/plan.py --serving --serving-config 345m       # serving space

``--json`` prints one machine-readable document (``bench.py`` consumes
it for the ``gpt_13b_planned_predicted`` row; ``Engine.prepare(plan=)``
accepts the ``best`` entry's mesh degrees verbatim).
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _table(rows, cols):
    head = [c[0] for c in cols]
    body = [[str(c[1](r)) for c in cols] for r in rows]
    widths = [max(len(h), *(len(b[i]) for b in body)) if body else len(h)
              for i, h in enumerate(head)]
    fmt = "  ".join(f"{{:<{w}}}" for w in widths)
    lines = [fmt.format(*head), fmt.format(*("-" * w for w in widths))]
    lines += [fmt.format(*b) for b in body]
    return "\n".join(lines)


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="rank parallelism plans from the static cost model "
                    "(trace-only, any host, no devices)")
    ap.add_argument("--model", default="gpt_13b",
                    choices=["gpt_tiny", "gpt_345m", "gpt_1p3b",
                             "gpt_13b"])
    ap.add_argument("--devices", type=int, default=16,
                    help="slice size N to factor into dp*mp*pp*sharding")
    ap.add_argument("--chip", default="v5e")
    ap.add_argument("--global-batch", type=int, default=0,
                    help="0 = the model's bench default")
    ap.add_argument("--seq", type=int, default=0,
                    help="0 = the model's bench default")
    ap.add_argument("--top-k", type=int, default=5)
    ap.add_argument("--max-traces", type=int, default=12,
                    help="trace budget: finalists priced by the "
                         "trace-based model")
    ap.add_argument("--json", action="store_true",
                    help="one JSON document instead of the table")
    ap.add_argument("--serving", action="store_true",
                    help="search the serving plan space (decode bucket, "
                         "page size, quantize) instead of training")
    ap.add_argument("--serving-config", default="345m",
                    choices=["tiny", "345m", "1.3b", "13b"])
    args = ap.parse_args(argv)

    if not os.environ.get("_PLAN_RESPAWNED"):
        # force the CPU backend in a fresh process BEFORE jax
        # initializes (the sitecustomize force-selects the TPU):
        # planning is trace-only and must never wait on a wedged chip
        env = dict(os.environ, _PLAN_RESPAWNED="1", JAX_PLATFORMS="cpu")
        return subprocess.run(
            [sys.executable, os.path.abspath(__file__)]
            + (argv if argv is not None else sys.argv[1:]),
            env=env).returncode

    sys.path.insert(0, REPO)
    import jax
    jax.config.update("jax_platforms", "cpu")

    if args.serving:
        from paddle_tpu.distributed.auto_parallel.planner import \
            plan_serving
        out = plan_serving(args.serving_config, chip=args.chip,
                           top_k=args.top_k)
        if args.json:
            out.pop("pruned")
            print(json.dumps(out), flush=True)
            return 0
        print(f"serving plans: {args.serving_config} on {out['chip']} "
              f"({out['planner_s']}s, {out['n_pruned']} pruned)")
        print(_table(out["plans"], [
            ("concurrency", lambda r: r["concurrency"]),
            ("page_size", lambda r: r["page_size"]),
            ("quantize", lambda r: r["quantize"] or "-"),
            ("tok/s", lambda r: r["predicted_tokens_per_sec"]),
            ("step_ms", lambda r: r["predicted_decode_step_ms"]),
            ("hbm_mb", lambda r: r["hbm_mb"]),
            ("bound", lambda r: r["predicted_bound"]),
        ]))
        return 0

    from paddle_tpu.distributed.auto_parallel.planner import plan_gpt
    report = plan_gpt(args.model, devices=args.devices, chip=args.chip,
                      global_batch=args.global_batch or None,
                      seq_len=args.seq or None, top_k=args.top_k,
                      max_traces=args.max_traces)
    doc = report.as_dict()
    doc["best"] = report.best.as_dict() if report.plans else None
    if args.json:
        print(json.dumps(doc), flush=True)
        return 0
    print(f"plans: {args.model} on {args.devices}x {doc['chip']} "
          f"(planner {doc['planner_s']}s, {doc['n_candidates']} "
          f"candidates, {doc['n_traced']} traced, {doc['n_pruned']} "
          f"pruned)")
    print(_table([p.as_dict() for p in report.plans], [
        ("mesh", lambda r: r["mesh"]),
        ("n_micro", lambda r: r["n_micro"]),
        ("remat", lambda r: r["remat"]),
        ("wire", lambda r: r["wire_dtype"] or "-"),
        ("step_ms", lambda r: r["step_ms"]),
        ("MFU", lambda r: r["predicted_mfu"]),
        ("peak_hbm_gb", lambda r: r["peak_hbm_gb"]),
        ("bound", lambda r: r["bound"]),
        ("tok/s/chip", lambda r: r["tokens_per_sec_per_chip"]),
    ]))
    return 0


if __name__ == "__main__":
    sys.exit(main())
