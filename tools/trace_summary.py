"""Aggregate-table CLI over an exported ``.paddle_trace.json``.

Post-hoc counterpart of ``Profiler.summary()`` — same aggregation and
table code (``profiler.profiler.aggregate_events`` / ``format_agg_table``)
applied to a chrome-trace file instead of a live Profiler, so a trace
shipped from a training run can be read without rerunning anything.

Usage::

    python tools/trace_summary.py run/host_123.paddle_trace.json
    python tools/trace_summary.py trace.json --top 20 --unit us
"""
import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from paddle_tpu.profiler.profiler import (  # noqa: E402
    aggregate_events, format_agg_table,
)


def load_trace(path):
    """Return (span_events, counter_events) from a chrome-trace JSON."""
    with open(path) as f:
        doc = json.load(f)
    # both chrome-trace container forms: {"traceEvents": [...]} and bare array
    events = doc if isinstance(doc, list) else doc.get("traceEvents", [])
    spans = [e for e in events if e.get("ph") == "X"]
    counters = [e for e in events if e.get("ph") == "C"]
    return spans, counters


def summarize(path, top=None, time_unit="ms"):
    """Build the report lines for one trace file."""
    spans, counters = load_trace(path)
    # chrome trace ts/dur are µs; the shared aggregator takes ns
    agg = aggregate_events(
        (e.get("name", "?"), float(e.get("dur", 0.0)) * 1e3) for e in spans)
    lines = [f"{path}: {len(spans)} spans, {len(counters)} counter samples"]
    if agg:
        lines.extend(format_agg_table(agg, time_unit=time_unit, top=top))
    else:
        lines.append("(no span events)")
    by_counter = {}
    for e in counters:
        args = e.get("args") or {}
        v = args.get("value", next(iter(args.values()), None)) \
            if args else None
        if v is None:
            continue
        cur = by_counter.setdefault(e.get("name", "?"),
                                    {"n": 0, "min": v, "max": v, "last": v})
        cur["n"] += 1
        cur["min"] = min(cur["min"], v)
        cur["max"] = max(cur["max"], v)
        cur["last"] = v
    for name, c in sorted(by_counter.items()):
        lines.append(f"counter {name}: n={c['n']} min={c['min']:.0f} "
                     f"max={c['max']:.0f} last={c['last']:.0f}")
    return lines


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="top-N aggregate table over a .paddle_trace.json")
    ap.add_argument("trace", nargs="+", help="exported chrome-trace file(s)")
    ap.add_argument("--top", type=int, default=None,
                    help="show only the N slowest names")
    ap.add_argument("--unit", default="ms", choices=["s", "ms", "us", "ns"])
    args = ap.parse_args(argv)
    for path in args.trace:
        print("\n".join(summarize(path, top=args.top, time_unit=args.unit)))
    return 0


if __name__ == "__main__":
    sys.exit(main())
