"""Aggregate-table CLI over an exported ``.paddle_trace.json``.

Post-hoc counterpart of ``Profiler.summary()`` — same aggregation and
table code (``profiler.profiler.aggregate_events`` / ``format_agg_table``)
applied to a chrome-trace file instead of a live Profiler, so a trace
shipped from a training run can be read without rerunning anything.

``--diff A B`` compares two traces (a good round vs a slow one): top-N
table of per-op-span total-time deltas, sorted by how much each name
moved — the op-level view the perf doctor's step-level attribution
points into.

Op-attribution files (``paddle_tpu.observability.opprof`` output —
``{"schema": "op_attribution", ...}``) are accepted everywhere a trace
is: each row becomes a span named by its site with its measured time,
so the same table/diff plumbing compares two attribution runs
site-by-site. ``--ops`` switches to the richer attribution view
(measured vs predicted, family rollup, sum-to-total line).

Usage::

    python tools/trace_summary.py run/host_123.paddle_trace.json
    python tools/trace_summary.py trace.json --top 20 --unit us
    python tools/trace_summary.py --diff good.json slow.json --top 15
    python tools/trace_summary.py attribution.json --ops
    python tools/trace_summary.py --diff attr_a.json attr_b.json
"""
import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from paddle_tpu.profiler.profiler import (  # noqa: E402
    aggregate_events, format_agg_table,
)


def _is_attribution(doc) -> bool:
    return isinstance(doc, dict) and (
        doc.get("schema") == "op_attribution"
        or ("rows" in doc and "measured_total_ms" in doc))


def _attribution_spans(doc):
    """Synthesized chrome spans from an op-attribution table: one span
    per site, dur = measured time (ms → µs) — so the aggregate/diff
    plumbing treats attribution files exactly like traces."""
    return [{"ph": "X", "name": r.get("site", "?"), "ts": 0.0,
             "dur": float(r.get("measured_ms") or 0.0) * 1e3}
            for r in doc.get("rows") or ()]


def load_trace(path):
    """Return (span_events, counter_events) from a chrome-trace JSON
    (or an op-attribution JSON, rows synthesized into spans)."""
    with open(path) as f:
        doc = json.load(f)
    if _is_attribution(doc):
        return _attribution_spans(doc), []
    # both chrome-trace container forms: {"traceEvents": [...]} and bare array
    events = doc if isinstance(doc, list) else doc.get("traceEvents", [])
    spans = [e for e in events if e.get("ph") == "X"]
    counters = [e for e in events if e.get("ph") == "C"]
    return spans, counters


def summarize(path, top=None, time_unit="ms"):
    """Build the report lines for one trace file."""
    spans, counters = load_trace(path)
    # chrome trace ts/dur are µs; the shared aggregator takes ns
    agg = aggregate_events(
        (e.get("name", "?"), float(e.get("dur", 0.0)) * 1e3) for e in spans)
    lines = [f"{path}: {len(spans)} spans, {len(counters)} counter samples"]
    if agg:
        lines.extend(format_agg_table(agg, time_unit=time_unit, top=top))
    else:
        lines.append("(no span events)")
    by_counter = {}
    for e in counters:
        args = e.get("args") or {}
        v = args.get("value", next(iter(args.values()), None)) \
            if args else None
        if v is None:
            continue
        cur = by_counter.setdefault(e.get("name", "?"),
                                    {"n": 0, "min": v, "max": v, "last": v})
        cur["n"] += 1
        cur["min"] = min(cur["min"], v)
        cur["max"] = max(cur["max"], v)
        cur["last"] = v
    for name, c in sorted(by_counter.items()):
        lines.append(f"counter {name}: n={c['n']} min={c['min']:.0f} "
                     f"max={c['max']:.0f} last={c['last']:.0f}")
    return lines


_UNIT_DIV = {"s": 1e9, "ms": 1e6, "us": 1e3, "ns": 1.0}


def diff_summarize(path_a, path_b, top=None, time_unit="ms"):
    """Top-N per-span-name deltas (B − A) between two traces, by total
    time moved; names present in only one trace count from zero."""
    aggs = []
    for path in (path_a, path_b):
        spans, _ = load_trace(path)
        aggs.append(aggregate_events(
            (e.get("name", "?"), float(e.get("dur", 0.0)) * 1e3)
            for e in spans))
    agg_a, agg_b = aggs
    div = _UNIT_DIV[time_unit]
    deltas = []
    for name in set(agg_a) | set(agg_b):
        cnt_a, tot_a = agg_a.get(name, (0, 0.0))
        cnt_b, tot_b = agg_b.get(name, (0, 0.0))
        deltas.append((name, cnt_a, cnt_b, tot_a / div, tot_b / div,
                       (tot_b - tot_a) / div))
    deltas.sort(key=lambda d: -abs(d[5]))
    if top:
        dropped = len(deltas) - top
        deltas = deltas[:top]
    else:
        dropped = 0
    u = time_unit
    lines = [f"trace diff: A={path_a}  B={path_b}",
             f"{'name':<44} {'calls A>B':>12} {'total A(' + u + ')':>14} "
             f"{'total B(' + u + ')':>14} {'Δ(' + u + ')':>12}"]
    lines.append("-" * len(lines[1]))
    for name, ca, cb, ta, tb, d in deltas:
        lines.append(f"{name[:44]:<44} {f'{ca}>{cb}':>12} {ta:>14.3f} "
                     f"{tb:>14.3f} {d:>+12.3f}")
    if dropped > 0:
        lines.append(f"... {dropped} more name(s) below the top-{top} cut")
    total = sum(d[5] for d in deltas)
    lines.append(f"net span-time delta (shown rows): {total:+.3f}{u}")
    return lines


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="top-N aggregate table over a .paddle_trace.json "
                    "(or --diff two traces)")
    ap.add_argument("trace", nargs="+", help="exported chrome-trace file(s)")
    ap.add_argument("--top", type=int, default=None,
                    help="show only the N slowest names")
    ap.add_argument("--unit", default="ms", choices=["s", "ms", "us", "ns"])
    ap.add_argument("--diff", action="store_true",
                    help="compare exactly two traces: top-N op-span "
                         "total-time deltas (B − A)")
    ap.add_argument("--ops", action="store_true",
                    help="attribution files only: the measured-vs-"
                         "predicted op table instead of the span table")
    args = ap.parse_args(argv)
    if args.ops:
        from paddle_tpu.observability.doctor import format_ops_table
        rc = 0
        for path in args.trace:
            with open(path) as f:
                doc = json.load(f)
            if not _is_attribution(doc):
                print(f"{path}: not an op-attribution file (generate one "
                      f"with paddle_tpu.observability.opprof)",
                      file=sys.stderr)
                rc = 2
                continue
            print(format_ops_table(doc, top=args.top or 10))
        return rc
    if args.diff:
        if len(args.trace) != 2:
            ap.error("--diff takes exactly two trace files")
        print("\n".join(diff_summarize(args.trace[0], args.trace[1],
                                       top=args.top, time_unit=args.unit)))
        return 0
    for path in args.trace:
        print("\n".join(summarize(path, top=args.top, time_unit=args.unit)))
    return 0


if __name__ == "__main__":
    sys.exit(main())
